"""Operator-registry parity: legacy aliases and long-tail ops.

The reference exposes several generations of the same API surface —
CamelCase legacy names (``_Plus``, registered via
MXNET_REGISTER_OP_PROPERTY), deprecated v1 layers (``Convolution_v1``),
and assorted long-tail operators that never grew a family module here.
This module closes the audited gap (see ``tests/test_op_parity.py``)
with:

- pure alias registrations onto the canonical implementations, and
- implementations of the remaining user-visible operators: SVMOutput
  (svm_output.cc), IdentityAttachKLSparseReg
  (identity_attach_KL_sparse_reg.cc), legacy Crop (crop.cc),
  hard_sigmoid / shape_array / size_array
  (elemwise_unary_op_basic.cc), slice/crop assignment (matrix_op.cc),
  multisample distributions (multisample_op.cc), group-adagrad
  (contrib/optimizer_op.cc), bipartite matching
  (contrib/bounding_box.cc:148), and deformable PSROI pooling
  (contrib/deformable_psroi_pooling.cc).

Graph-level sparse ops (cast_storage / _sparse_retain / _square_sum,
reference cast_storage.cc / sparse_retain.cc / square_sum.cc) are
registered here with DENSE-array semantics: under jit/XLA every traced
value is dense, and sparse storage is an eager/kvstore representation
(``mxnet_tpu.ndarray.sparse``), so the graph ops are the semantic
projections (identity / row filter / squared reduction) that make
``mx.sym`` sparse configurations runnable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, alias
from .random_ops import _shape, np_dtype

__all__ = []


# ---------------------------------------------------------------------------
# alias parity: legacy CamelCase / deprecated spellings -> canonical ops
# ---------------------------------------------------------------------------

_ALIASES = {
    # elemwise binary (MXNET_REGISTER_OP_PROPERTY generation)
    "_Plus": "broadcast_add", "_Minus": "broadcast_sub",
    "_Mul": "broadcast_mul", "_Div": "broadcast_div",
    "_Mod": "broadcast_mod", "_Power": "broadcast_power",
    "_Maximum": "broadcast_maximum", "_Minimum": "broadcast_minimum",
    "_Hypot": "broadcast_hypot",
    "_add": "broadcast_add", "_sub": "broadcast_sub",
    "_grad_add": "broadcast_add",
    "broadcast_plus": "broadcast_add", "broadcast_minus": "broadcast_sub",
    # comparison / logic
    "_Equal": "_equal", "_Not_Equal": "_not_equal",
    "_Greater": "_greater", "_Greater_Equal": "_greater_equal",
    "_Lesser": "_lesser", "_Lesser_Equal": "_lesser_equal",
    "_Logical_And": "broadcast_logical_and",
    "_Logical_Or": "broadcast_logical_or",
    "_Logical_Xor": "broadcast_logical_xor",
    "_logical_and": "broadcast_logical_and",
    "_logical_or": "broadcast_logical_or",
    "_logical_xor": "broadcast_logical_xor",
    # scalar variants
    "_PlusScalar": "_plus_scalar", "_MinusScalar": "_minus_scalar",
    "_RMinusScalar": "_rminus_scalar", "_MulScalar": "_mul_scalar",
    "_DivScalar": "_div_scalar", "_RDivScalar": "_rdiv_scalar",
    "_ModScalar": "_mod_scalar", "_RModScalar": "_rmod_scalar",
    "_PowerScalar": "_power_scalar", "_RPowerScalar": "_rpower_scalar",
    "_MaximumScalar": "_maximum_scalar",
    "_MinimumScalar": "_minimum_scalar",
    "_HypotScalar": "_hypot_scalar",
    "_EqualScalar": "_equal_scalar",
    "_NotEqualScalar": "_not_equal_scalar",
    "_GreaterScalar": "_greater_scalar",
    "_GreaterEqualScalar": "_greater_equal_scalar",
    "_LesserScalar": "_lesser_scalar",
    "_LesserEqualScalar": "_lesser_equal_scalar",
    "_LogicalAndScalar": "_logical_and_scalar",
    "_LogicalOrScalar": "_logical_or_scalar",
    "_LogicalXorScalar": "_logical_xor_scalar",
    # random sampling (sample_op.cc registers random_* aliases)
    "random_uniform": "_random_uniform",
    "random_normal": "_random_normal",
    "random_gamma": "_random_gamma",
    "random_exponential": "_random_exponential",
    "random_poisson": "_random_poisson",
    "random_negative_binomial": "_random_negative_binomial",
    "random_generalized_negative_binomial":
        "_random_generalized_negative_binomial",
    # deprecated spellings of modern layers/ops
    "crop": "slice",                       # matrix_op.cc: crop == slice
    "_rnn_param_concat": "concat",         # concat with RNN shape-infer
    "BatchNorm_v1": "BatchNorm",
    "Convolution_v1": "Convolution",
    "Pooling_v1": "Pooling",
    "_contrib_box_non_maximum_suppression": "_contrib_box_nms",
    "_copyto": "_copy",
    # the reference splits single-image Proposal from batched
    # MultiProposal (multi_proposal.cc); our Proposal vmaps over the
    # batch already, so they are the same op
    "_contrib_MultiProposal": "_contrib_Proposal",
    "MultiProposal": "_contrib_Proposal",
    # Embedding with a row_sparse gradient: storage layout is a kvstore
    # concern here, compute is identical (indexing_op.cc:SparseEmbedding)
    "_contrib_SparseEmbedding": "Embedding",
}

for _name, _target in _ALIASES.items():
    alias(_name, _target)


# ---------------------------------------------------------------------------
# elemwise long tail
# ---------------------------------------------------------------------------

@register_op("hard_sigmoid")
def _hard_sigmoid(x, alpha=0.2, beta=0.5):
    """Piecewise-linear sigmoid (elemwise_unary_op_basic.cc)."""
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register_op("shape_array")
def _shape_array(x):
    """Shape of the input as a 1-d integer array.  The reference emits
    int64; on TPU the native integer width is 32-bit and jax truncates
    int64 unless x64 mode is on, so the widest enabled int is used."""
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.array(x.shape, dt)


@register_op("size_array")
def _size_array(x):
    """Total element count as a 1-element integer array (see
    shape_array for the int width note)."""
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return jnp.array([x.size], dt)


@register_op("_zeros_without_dtype")
def _zeros_without_dtype(shape=(), ctx=None, dtype=-1):
    """Zeros with an unspecified dtype defaulting to float32
    (init_op.cc); the -1 sentinel mirrors the reference's parameter."""
    dt = "float32" if dtype in (-1, None) else dtype
    return jnp.zeros(_shape(shape), np_dtype(dt))


@register_op("_identity_with_attr_like_rhs",
             input_names=("lhs", "rhs"))
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs carrying rhs's shape/storage attributes during
    graph passes (elemwise_unary_op_basic.cc); rhs is unused by the
    computation and therefore gets zero gradient."""
    del rhs
    return lhs + 0


@register_op("_scatter_minus_scalar")
def _scatter_minus_scalar(x, scalar=0.0):
    """Scalar minus applied only to stored (non-zero) elements of a
    sparse input in the reference (elemwise_scatter_op.cc); dense
    arrays store everything, so it is x - scalar."""
    return x - scalar


@register_op("_scatter_elemwise_div", input_names=("lhs", "rhs"))
def _scatter_elemwise_div(lhs, rhs):
    """Divide writing only the lhs-stored elements (sparse storage
    optimization in elemwise_scatter_op.cc); dense semantics: lhs/rhs."""
    return lhs / rhs


# ---------------------------------------------------------------------------
# slice / crop assignment (matrix_op.cc)
# ---------------------------------------------------------------------------

def _norm_slice(shape, begin, end, step):
    slc = []
    step = step or (1,) * len(begin)
    for d, (b, e) in enumerate(zip(begin, end)):
        st = int(step[d]) if d < len(step) and step[d] is not None else 1
        b = 0 if b is None else int(b)
        e = shape[d] if e is None else int(e)
        if b < 0:
            b += shape[d]
        if e < 0:
            e += shape[d]
        slc.append(slice(b, e, st))
    for d in range(len(begin), len(shape)):
        slc.append(slice(None))
    return tuple(slc)


@register_op("_slice_assign", input_names=("lhs", "rhs"),
             aliases=("_crop_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """Write rhs into lhs[begin:end:step] (matrix_op.cc _slice_assign;
    _crop_assign is its deprecated name)."""
    return lhs.at[_norm_slice(lhs.shape, begin, end, step)].set(rhs)


@register_op("_slice_assign_scalar",
             aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_norm_slice(data.shape, begin, end, step)].set(scalar)


# ---------------------------------------------------------------------------
# legacy Crop layer (crop.cc)
# ---------------------------------------------------------------------------

@register_op("Crop")
def _crop_layer(*args, offset=(0, 0), h_w=(0, 0), center_crop=False,
                num_args=None):
    """Crop the spatial dims of an NCHW input, either to an explicit
    ``h_w`` or to match a second input's H/W (crop.cc).  With
    ``center_crop`` the window is centered; otherwise ``offset`` is the
    top-left corner."""
    data = args[0]
    H, W = data.shape[2], data.shape[3]
    if len(args) > 1:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]


# ---------------------------------------------------------------------------
# SVM / sparse-regularizer output layers
# ---------------------------------------------------------------------------

@register_op("SVMOutput")
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """Multiclass SVM output (svm_output.cc:107): forward is the
    identity on the scores; backward ignores the incoming cotangent and
    emits the hinge-loss gradient (L1-SVM when ``use_linear`` else
    squared-hinge L2-SVM), scaled by ``regularization_coefficient``."""

    @jax.custom_vjp
    def f(d, l):
        return d + 0

    def fwd(d, l):
        return d + 0, (d, l)

    def bwd(res, g):
        d, l = res
        li = l.astype(jnp.int32)
        n_class = d.shape[-1]
        onehot = jax.nn.one_hot(li, n_class, dtype=d.dtype)
        score_y = jnp.take_along_axis(d, li[..., None], axis=-1)
        viol = jnp.maximum(margin - (score_y - d), 0.0) * (1 - onehot)
        if use_linear:                      # L1-SVM: subgradient
            gj = (viol > 0).astype(d.dtype)
        else:                               # L2-SVM: 2 * violation
            gj = 2.0 * viol
        grad = gj - onehot * jnp.sum(gj, axis=-1, keepdims=True)
        return (regularization_coefficient * grad.astype(d.dtype),
                jnp.zeros_like(l))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register_op("IdentityAttachKLSparseReg")
def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                   penalty=0.001, momentum=0.9):
    """Identity forward with a KL-divergence sparsity penalty added to
    the gradient (identity_attach_KL_sparse_reg.cc): treats mean
    activation per unit as a Bernoulli rate rho_hat and adds
    penalty * d KL(rho || rho_hat) / d x.  The reference's momentum
    smoothing of rho_hat is an aux-state detail; here rho_hat is the
    batch mean (momentum has no effect inside a pure graph)."""
    rho = sparseness_target

    @jax.custom_vjp
    def f(d):
        return d + 0

    def fwd(d):
        return d + 0, d

    def bwd(d, g):
        rho_hat = jnp.clip(jnp.mean(d, axis=0), 1e-6, 1 - 1e-6)
        kl_grad = (-rho / rho_hat + (1 - rho) / (1 - rho_hat)) / d.shape[0]
        return (g + penalty * jnp.broadcast_to(kl_grad, d.shape)
                .astype(d.dtype),)

    f.defvjp(fwd, bwd)
    return f(data)


# ---------------------------------------------------------------------------
# multisample distributions (multisample_op.cc): per-row parameters
# ---------------------------------------------------------------------------

@register_op("_sample_exponential", needs_rng=True)
def _sample_exponential(rng, lam, shape=(), dtype="float32"):
    s = _shape(shape)
    e = jax.random.exponential(rng, lam.shape + s, np_dtype(dtype))
    return e / lam.reshape(lam.shape + (1,) * len(s))


@register_op("_sample_poisson", needs_rng=True)
def _sample_poisson(rng, lam, shape=(), dtype="float32"):
    s = _shape(shape)
    lam_b = jnp.broadcast_to(lam.reshape(lam.shape + (1,) * len(s)),
                             lam.shape + s)
    return jax.random.poisson(rng, lam_b).astype(np_dtype(dtype))


def _neg_binomial(rng, k, p, dtype):
    """NB(k, p) == Poisson(Gamma(k, (1-p)/p)) (gamma-Poisson mixture)."""
    kg, kp = jax.random.split(rng)
    rate = jax.random.gamma(kg, k) * (1.0 - p) / p
    return jax.random.poisson(kp, rate).astype(dtype)


@register_op("_sample_negative_binomial", needs_rng=True)
def _sample_negative_binomial(rng, k, p, shape=(), dtype="float32"):
    s = _shape(shape)
    kb = jnp.broadcast_to(k.reshape(k.shape + (1,) * len(s)), k.shape + s)
    pb = jnp.broadcast_to(p.reshape(p.shape + (1,) * len(s)), p.shape + s)
    return _neg_binomial(rng, kb, pb, np_dtype(dtype))


@register_op("_sample_generalized_negative_binomial", needs_rng=True)
def _sample_gen_negative_binomial(rng, mu, alpha, shape=(),
                                  dtype="float32"):
    """GNB(mu, alpha): Poisson rate drawn from Gamma(1/alpha, mu*alpha)."""
    s = _shape(shape)
    mub = jnp.broadcast_to(mu.reshape(mu.shape + (1,) * len(s)),
                           mu.shape + s)
    ab = jnp.broadcast_to(alpha.reshape(alpha.shape + (1,) * len(s)),
                          alpha.shape + s)
    kg, kp = jax.random.split(rng)
    rate = jax.random.gamma(kg, 1.0 / ab) * mub * ab
    return jax.random.poisson(kp, rate).astype(np_dtype(dtype))


# ---------------------------------------------------------------------------
# group adagrad (contrib/optimizer_op.cc)
# ---------------------------------------------------------------------------

@register_op("_contrib_group_adagrad_update",
             input_names=("weight", "grad", "history"),
             num_outputs=2, num_visible_outputs=1, donate=(0, 2))
def _group_adagrad_update(weight, grad, history, lr=0.01,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          epsilon=1e-5):
    """Adagrad with one accumulator per row (embedding-friendly):
    history[r] += mean(grad[r]^2); w[r] -= lr * grad[r] /
    sqrt(history[r] + eps)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    ssq = jnp.mean(g * g, axis=tuple(range(1, g.ndim)))
    hist = history + ssq
    denom = jnp.sqrt(hist + epsilon)
    w = weight - lr * g / denom.reshape((-1,) + (1,) * (g.ndim - 1))
    return w, hist


# ---------------------------------------------------------------------------
# bipartite matching (contrib/bounding_box.cc:148)
# ---------------------------------------------------------------------------

@register_op("_contrib_bipartite_matching", num_outputs=2)
def _bipartite_matching(data, is_ascend=False, threshold=1e-12, topk=-1):
    """Greedy bipartite matching on a score matrix (..., N, M).

    Returns (x, y): x[r] = matched column of row r (-1 if unmatched),
    y[c] = matched row of column c.  Matching picks the globally best
    remaining score each round, stopping at ``threshold`` or after
    ``topk`` matches.  Gradients are zero (the reference routes none)."""
    d = jax.lax.stop_gradient(data)
    *batch, n, m = d.shape
    d2 = d.reshape((-1, n, m))
    sign = 1.0 if is_ascend else -1.0
    rounds = min(n, m) if topk is None or topk <= 0 else min(n, m, topk)
    big = jnp.asarray(jnp.inf, d.dtype)

    def one(mat):
        def body(carry, _):
            mat, x, y = carry
            flat = jnp.argmin(sign * mat)   # best remaining score
            r, c = flat // m, flat % m
            score = mat[r, c]
            ok = (score >= threshold) if not is_ascend \
                else (score <= threshold)
            x = jnp.where(ok, x.at[r].set(c), x)
            y = jnp.where(ok, y.at[c].set(r), y)
            mat = jnp.where(ok, mat.at[r, :].set(sign * big)
                            .at[:, c].set(sign * big), mat)
            return (mat, x, y), None

        x0 = jnp.full((n,), -1, jnp.int32)
        y0 = jnp.full((m,), -1, jnp.int32)
        (_, x, y), _ = jax.lax.scan(body, (mat, x0, y0), None,
                                    length=rounds)
        return x, y

    x, y = jax.vmap(one)(d2)
    out_dt = data.dtype
    return (x.reshape(tuple(batch) + (n,)).astype(out_dt),
            y.reshape(tuple(batch) + (m,)).astype(out_dt))


# ---------------------------------------------------------------------------
# deformable PSROI pooling (contrib/deformable_psroi_pooling.cc)
# ---------------------------------------------------------------------------

@register_op("_contrib_DeformablePSROIPooling",
             input_names=("data", "rois", "trans"), num_outputs=2,
             num_visible_outputs=1)
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=0, group_size=1, pooled_size=0,
                              part_size=0, sample_per_part=1,
                              trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling (R-FCN / Deformable
    ConvNets): like PSROIPooling but each bin's sampling window is
    shifted by a learned normalized offset from ``trans``
    (shape (num_rois, 2, part, part)), scaled by ``trans_std`` and the
    ROI size.  Sampling uses ``sample_per_part``^2 bilinear taps per
    bin.  Second output is the sampling-count map (the reference keeps
    it for backward; exposed but hidden from user graphs)."""
    g = int(group_size)
    k = int(pooled_size)
    part = int(part_size) if part_size else k
    sp = max(int(sample_per_part), 1)
    od = int(output_dim)
    N, C, H, W = data.shape
    nroi = rois.shape[0]
    if trans is None or no_trans:
        trans_eff = jnp.zeros((nroi, 2, part, part), data.dtype)
    else:
        trans_eff = trans.reshape(nroi, 2, part, part) * trans_std

    cls_idx = jnp.arange(od)
    gi = jnp.minimum((jnp.arange(k) * g) // k, g - 1)

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / k
        bin_h = rh / k
        img = data[bidx]                       # (C, H, W)

        # per-bin offsets, indexed on the part grid
        pi = jnp.minimum((jnp.arange(k) * part) // k, part - 1)
        dy = tr[0][pi][:, pi] * rh             # (k, k)
        dx = tr[1][pi][:, pi] * rw

        # sample grid inside each bin; absolute coords: (k, k, sp, sp)
        sub = (jnp.arange(sp, dtype=data.dtype) + 0.5) / sp
        yy = (y1 + (jnp.arange(k, dtype=data.dtype)[:, None, None, None]
                    + sub[None, None, :, None]) * bin_h + dy[:, :, None,
                                                             None])
        xx = (x1 + (jnp.arange(k, dtype=data.dtype)[None, :, None, None]
                    + sub[None, None, None, :]) * bin_w + dx[:, :, None,
                                                             None])
        yy = jnp.clip(yy, 0.0, H - 1.0)
        xx = jnp.clip(xx, 0.0, W - 1.0)
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        y1i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
        x1i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
        y2i = jnp.clip(y1i + 1, 0, H - 1)
        x2i = jnp.clip(x1i + 1, 0, W - 1)
        wy = yy - y0
        wx = xx - x0

        # position-sensitive channel per (class, bin-row, bin-col)
        chan = (cls_idx[:, None, None] * g * g +
                gi[None, :, None] * g + gi[None, None, :])  # (od, k, k)

        def gather(yi, xi):
            # img[chan, yi, xi] -> (od, k, k, sp, sp)
            return img[chan[..., None, None],
                       yi[None, ...], xi[None, ...]]

        val = ((1 - wy) * (1 - wx) * gather(y1i, x1i) +
               (1 - wy) * wx * gather(y1i, x2i) +
               wy * (1 - wx) * gather(y2i, x1i) +
               wy * wx * gather(y2i, x2i))
        out = val.mean(axis=(-2, -1))          # (od, k, k)
        cnt = jnp.full((od, k, k), float(sp * sp), data.dtype)
        return out, cnt

    out, cnt = jax.vmap(one_roi)(rois, trans_eff)
    return out, cnt


# ---------------------------------------------------------------------------
# graph-level sparse ops (dense semantics under XLA; see module docstring)
# ---------------------------------------------------------------------------

@register_op("cast_storage")
def _cast_storage_op(data, stype="default"):
    """Storage-format cast (cast_storage.cc:71).  A CSR carrier bound
    as a graph input densifies for real (gather/scatter lowering, see
    ops/sparse_graph.py).  Dense->sparse inside a graph stays a tagged
    identity: the nnz of a traced value is data-dependent, which XLA's
    static shapes cannot express — the eager layer (ndarray/sparse.py
    cast_storage) does the real conversion outside jit."""
    from .sparse_graph import CsrCarrier
    if isinstance(data, CsrCarrier):
        if stype in ("default", "row_sparse"):
            return data.todense()
        return data
    return data + 0


@register_op("_sparse_retain", input_names=("data", "indices"))
def _sparse_retain_op(data, indices):
    """Keep only the listed rows, zeroing the rest (sparse_retain.cc).
    Dense projection of the row_sparse retain."""
    keep = jnp.zeros((data.shape[0],), jnp.bool_)
    keep = keep.at[indices.astype(jnp.int32)].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)),
                     data, 0)


@register_op("_square_sum")
def _square_sum_op(data, axis=None, keepdims=False):
    """sum(x^2) along axis (square_sum.cc) — the fused kernel the
    reference uses for row_sparse L2; XLA fuses the square into the
    reduction automatically."""
    ax = None if axis is None else (int(axis) if not
                                    isinstance(axis, (tuple, list))
                                    else tuple(int(a) for a in axis))
    return jnp.sum(data * data, axis=ax, keepdims=bool(keepdims))
