"""Control-flow operators — subgraphs compiled to XLA structured control
flow.

Reference capability: `src/operator/control_flow.cc` `_foreach` (:1255),
`_while_loop` (:1316), `_cond` (:1378) — subgraph-as-attribute ops run by
nested CachedOp loops on the engine.  The TPU-native design maps them
directly onto `lax.scan` / masked scan / `lax.cond`: the subgraph (a
Symbol) is a static op parameter, its evaluation function is built once
at trace time, and XLA compiles the whole loop into the surrounding
program — no per-iteration dispatch, differentiable by construction.

`_while_loop` uses a masked `lax.scan` over ``max_iterations`` rather than
`lax.while_loop`: reverse-mode autodiff through a dynamic while is not
defined, and the reference's symbolic while_loop is bounded by
``max_iterations`` anyway (outputs are padded; unexecuted steps are
zeros here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _subgraph_eval(subgraph, training):
    from ..executor import _build_eval
    return _build_eval(subgraph, training)


@register_op("_foreach", needs_rng=True, input_names=(),
             num_outputs=lambda p: int(p["n_outputs"]) + int(p["n_states"]))
def _foreach_op(rng, *arrays, subgraph=None, n_data=1, n_states=0,
                n_outputs=1, data_names=(), state_names=(),
                closure_names=(), training=True):
    """arrays = data (scanned on axis 0) + init states + closure values.

    subgraph outputs: [outputs..., new_states...] with names bound via
    data_names (per-step slices), state_names, closure_names.
    Returns (*stacked_outputs, *final_states).
    """
    n_data, n_states, n_outputs = int(n_data), int(n_states), int(n_outputs)
    data = arrays[:n_data]
    states = tuple(arrays[n_data:n_data + n_states])
    closure = arrays[n_data + n_states:]
    closure_map = dict(zip(closure_names, closure))
    eval_fn = _subgraph_eval(subgraph, training)

    def step(carry, xs):
        states, key = carry
        key, sub = jax.random.split(key)
        amap = dict(zip(data_names, xs))
        amap.update(zip(state_names, states))
        amap.update(closure_map)
        outs, _ = eval_fn(amap, {}, sub)
        return (tuple(outs[n_outputs:]), key), tuple(outs[:n_outputs])

    (final_states, _), ys = jax.lax.scan(step, (states, rng), tuple(data))
    return tuple(ys) + tuple(final_states)


@register_op("_while_loop", needs_rng=True, input_names=(),
             num_outputs=lambda p: int(p["n_outputs"]) +
                 int(p["n_loop_vars"]))
def _while_loop_op(rng, *arrays, cond_graph=None, func_graph=None,
                   max_iterations=0, n_loop_vars=1, n_outputs=1,
                   loop_var_names=(), cond_closure_names=(),
                   func_closure_names=(), training=True):
    """arrays = loop vars + cond closure + func closure.

    Runs ``func`` while ``cond`` is true, bounded by max_iterations
    (masked scan).  Returns (*stacked_outputs, *final_loop_vars);
    output rows beyond the executed step count are zeros.
    """
    n_loop_vars, n_outputs = int(n_loop_vars), int(n_outputs)
    max_iterations = int(max_iterations)
    lvars = tuple(arrays[:n_loop_vars])
    ncc = len(cond_closure_names)
    cond_clo = dict(zip(cond_closure_names,
                        arrays[n_loop_vars:n_loop_vars + ncc]))
    func_clo = dict(zip(func_closure_names, arrays[n_loop_vars + ncc:]))
    cond_fn = _subgraph_eval(cond_graph, training)
    func_fn = _subgraph_eval(func_graph, training)

    def pred(states, key):
        amap = dict(zip(loop_var_names, states))
        amap.update(cond_clo)
        outs, _ = cond_fn(amap, {}, key)
        return jnp.reshape(outs[0] != 0, ())

    def step(carry, _):
        states, done, key = carry
        key, k1, k2 = jax.random.split(key, 3)
        active = jnp.logical_and(jnp.logical_not(done), pred(states, k1))
        amap = dict(zip(loop_var_names, states))
        amap.update(func_clo)
        outs, _ = func_fn(amap, {}, k2)
        new_states = tuple(
            jnp.where(active, n, s)
            for n, s in zip(outs[n_outputs:], states))
        ys = tuple(jnp.where(active, o, jnp.zeros_like(o))
                   for o in outs[:n_outputs])
        return (new_states, jnp.logical_not(active), key), ys

    (final, _, _), ys = jax.lax.scan(
        step, (lvars, jnp.asarray(False), rng), None,
        length=max_iterations)
    return tuple(ys) + tuple(final)


@register_op("_cond", needs_rng=True, input_names=(),
             num_outputs=lambda p: int(p["n_outputs"]))
def _cond_op(rng, *arrays, pred_graph=None, then_graph=None,
             else_graph=None, n_outputs=1, pred_names=(), then_names=(),
             else_names=(), training=True):
    """arrays = pred inputs + then inputs + else inputs (by name lists).

    Evaluates pred_graph; selects then/else branch via lax.cond (only the
    taken branch executes at runtime).  Branches must produce the same
    output spec (reference requirement as well).
    """
    n_outputs = int(n_outputs)
    np_, nt = len(pred_names), len(then_names)
    pred_in = dict(zip(pred_names, arrays[:np_]))
    then_in = dict(zip(then_names, arrays[np_:np_ + nt]))
    else_in = dict(zip(else_names, arrays[np_ + nt:]))
    pred_fn = _subgraph_eval(pred_graph, training)
    then_fn = _subgraph_eval(then_graph, training)
    else_fn = _subgraph_eval(else_graph, training)
    k0, k1, k2 = jax.random.split(rng, 3)
    pred = jnp.reshape(pred_fn(pred_in, {}, k0)[0][0] != 0, ())

    def run_then(_):
        return tuple(then_fn(then_in, {}, k1)[0][:n_outputs])

    def run_else(_):
        return tuple(else_fn(else_in, {}, k2)[0][:n_outputs])

    return jax.lax.cond(pred, run_then, run_else, None)


@register_op("_subgraph_exec", needs_rng=True, input_names=(),
             num_outputs=lambda p: int(p["n_outputs"]))
def _subgraph_exec_op(rng, *inputs, subgraph=None, input_names=(),
                      n_outputs=1, training=False):
    """Execute a captured sub-Symbol as one unit (the replacement node
    the subgraph partitioner emits — reference counterpart: the
    subgraph op built by CreateSubgraphNode, subgraph_property.h:105).
    Inputs are bound to the subgraph's placeholder variables by name."""
    eval_fn = _subgraph_eval(subgraph, training)
    amap = dict(zip(input_names, inputs))
    outs, _aux = eval_fn(amap, {}, rng)
    return tuple(outs)
