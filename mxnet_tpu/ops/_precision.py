"""Matmul/conv precision policy.

On TPU the MXU natively multiplies bf16; XLA's DEFAULT precision lowers even
fp32 contractions to bf16 passes.  The reference framework is fp32-exact
(cuBLAS SGEMM), so fp32 inputs here use HIGHEST precision (3-pass bf16 on
TPU ≈ fp32), while bf16/fp16 inputs take the fast path — speed comes from
choosing bf16 dtypes, not from silently degrading fp32 math.  Override with
MXNET_TPU_MATMUL_PRECISION=default|high|highest.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_ENV = os.environ.get("MXNET_TPU_MATMUL_PRECISION", "")
_MAP = {"default": jax.lax.Precision.DEFAULT,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST}


def matmul_precision(*dtypes):
    """Precision for a contraction over operands of the given dtypes."""
    if _ENV:
        return _MAP[_ENV]
    if any(jnp.dtype(d) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))
           for d in dtypes):
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST
