"""Operator registry and eager dispatch.

TPU-native replacement for the reference's NNVM op registry + dependency
engine (reference: ``include/mxnet/op_attr_types.h:115-281`` attrs,
``src/imperative/imperative.cc:38-112`` Invoke/InvokeOp,
``src/engine/threaded_engine_perdevice.cc`` worker queues).

Design: an op is a *pure JAX function* ``fn(*arrays, **params)``.  Instead of
pushing kernels to a hand-written scheduler, eager invocation compiles the op
once per (param-set, input-aval) signature with ``jax.jit`` and reuses the
executable — XLA's async dispatch replaces the threaded engine; dependency
ordering comes for free from data flow; ``NDArray.asnumpy()`` is the sync
point (the reference's ``WaitToRead``).

Gradients are not registered per-op (the reference's ``FGradient``): autograd
obtains per-op VJPs from ``jax.vjp`` of the same pure function, and the graph
executor differentiates the whole fused program.
"""

from __future__ import annotations

import functools
import time as _time

import jax
import numpy as _np

__all__ = ["Op", "register_op", "get_op", "list_ops", "invoke", "alias",
           "iter_registrations", "op_contract"]

_OPS: dict[str, "Op"] = {}


class Op:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (e.g. ``dot``, ``Convolution``).
    fn : pure function ``fn(*jax_arrays, **params) -> array | tuple``.
    num_outputs : int or ``f(params) -> int``.
    needs_rng : if True, ``fn``'s first positional arg is a PRNG key supplied
        by the runtime (eager: ambient generator; executor: per-run key).
    donate : tuple of input indices whose buffers may be donated to outputs
        (optimizer update ops — gives true in-place HBM reuse under jit).
    """

    __slots__ = ("name", "fn", "num_outputs", "needs_rng", "donate", "doc",
                 "input_names", "num_visible_outputs", "param_names",
                 "aux_states", "active_inputs", "dynamic_params")

    def __init__(self, name, fn, num_outputs=1, needs_rng=False, donate=(),
                 doc=None, input_names=None, num_visible_outputs=None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.needs_rng = needs_rng
        self.donate = tuple(donate)
        self.doc = doc or fn.__doc__
        if input_names is None:
            input_names = _infer_input_names(fn, needs_rng)
        self.input_names = tuple(input_names)
        self.num_visible_outputs = num_visible_outputs
        self.param_names = _infer_param_names(fn)
        # aux_states: {input_idx: output_idx} — inputs that are mutable
        # auxiliary states (reference: BatchNorm moving stats); the output
        # at output_idx is the updated value the executor writes back.
        self.aux_states = {}
        # active_inputs: optional fn(params) -> tuple of input names actually
        # consumed (e.g. Convolution drops "bias" when no_bias=True)
        self.active_inputs = None
        # dynamic_params: scalar params passed as traced array args instead
        # of compile-time constants, so per-step values (lr, t, ...) do NOT
        # recompile the executable.  Critical on TPU where a compile is
        # O(10s) — an optimizer whose lr changes per step would otherwise
        # recompile every update.
        self.dynamic_params = ()

    def input_names_for(self, params):
        if self.active_inputs is None:
            return self.input_names
        return tuple(self.active_inputs(params))

    def n_out(self, params):
        if callable(self.num_outputs):
            return self.num_outputs(params)
        return self.num_outputs

    def n_visible(self, params):
        """Outputs surfaced to the user (the reference hides e.g. Dropout's
        mask and BatchNorm's saved stats unless requested)."""
        if self.num_visible_outputs is None:
            return self.n_out(params)
        if callable(self.num_visible_outputs):
            return self.num_visible_outputs(params)
        return self.num_visible_outputs

    def __repr__(self):
        return "Op(%s)" % self.name


def _infer_input_names(fn, needs_rng):
    """Array-input names from the fn signature: positional params without
    defaults are inputs (the rng key, if any, is skipped)."""
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return ()
    names = []
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD) \
                and p.default is inspect.Parameter.empty:
            names.append(p.name)
        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
            break
    if needs_rng and names:
        names = names[1:]
    return tuple(names)


def _infer_param_names(fn):
    """Op parameter names in signature order (params have defaults)."""
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return ()
    return tuple(p.name for p in sig.parameters.values()
                 if p.default is not inspect.Parameter.empty)


def register_op(name, num_outputs=1, needs_rng=False, donate=(), aliases=(),
                input_names=None, num_visible_outputs=None):
    """Decorator registering a pure JAX function as an operator."""
    def _reg(fn):
        op = Op(name, fn, num_outputs, needs_rng, donate,
                input_names=input_names,
                num_visible_outputs=num_visible_outputs)
        if name in _OPS:
            raise ValueError("op %r registered twice" % name)
        _OPS[name] = op
        for a in aliases:
            _OPS[a] = op
        return fn
    return _reg


def alias(name, target):
    _OPS[name] = _OPS[target]


def get_op(name):
    try:
        return _OPS[name]
    except KeyError:
        raise KeyError("operator %r is not registered" % (name,))


def list_ops():
    return sorted(_OPS)


def iter_registrations():
    """Yield ``(canonical_name, Op)`` once per registered op (aliases
    collapsed).  The runtime mirror of the static registration table
    tools/graftlint builds from the ``@register_op`` decorators — the
    registry cross-check test walks this to hold every op to the JG005
    contract."""
    seen = set()
    for name in sorted(_OPS):
        op = _OPS[name]
        if id(op) in seen:
            continue
        seen.add(id(op))
        yield op.name, op


_RNG_PARAM_NAMES = ("rng", "key", "rng_key", "prng_key", "prng")


def op_contract(op):
    """Statically-checkable contract facts for *op*, derived from its
    kernel signature (the JG005 invariants, computed at runtime so the
    cross-check test can't drift from the analyzer):

    - ``positional_params``: positional parameter names of ``op.fn``
    - ``array_arity``: count of array inputs (no-default positionals,
      rng excluded), or None when the kernel takes ``*args``
    - ``rng_param_ok``: needs_rng ops name their first positional
      parameter like a PRNG key (the runtime passes it positionally)
    - ``donate_valid``: every donate index addresses a real array input
    - ``input_names_consistent``: every declared input name is an
      actual positional parameter of the kernel, and the required
      (no-default) array params form a prefix of input_names — extra
      declared names must be optional array inputs like Convolution's
      ``bias=None``
    """
    import inspect
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return {"positional_params": (), "array_arity": None,
                "rng_param_ok": True, "donate_valid": True,
                "input_names_consistent": True}
    required, all_pos, has_var = [], [], False
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD):
            all_pos.append(p.name)
            if p.default is inspect.Parameter.empty:
                required.append(p.name)
        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
            has_var = True
    rng_ok = True
    arr = list(required)
    if op.needs_rng:
        rng_ok = bool(arr) and arr[0] in _RNG_PARAM_NAMES
        arr = arr[1:]
    arity = None if has_var else len(arr)
    donate_valid = True
    if op.donate and arity is not None:
        # donation may also target declared optional array inputs
        n_donatable = max(arity, len(op.input_names))
        donate_valid = all(0 <= i < n_donatable for i in op.donate)
    names_ok = True
    if not has_var and op.input_names:
        names_ok = (all(n in all_pos for n in op.input_names)
                    and list(op.input_names[:len(arr)]) == arr)
    return {"positional_params": tuple(required), "array_arity": arity,
            "rng_param_ok": rng_ok, "donate_valid": donate_valid,
            "input_names_consistent": names_ok}


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, _np.ndarray):
        return ("__nparr__", v.dtype.str, v.shape, v.tobytes())
    return v


def supports_donation():
    """Whether the active backend honors jit buffer donation — CPU
    PJRT does not (donating there only emits per-call warnings).  The
    single source of truth for every donate_argnums decision (eager op
    cache here, the fused train step, the jitted tree update)."""
    return jax.default_backend() != "cpu"


@functools.lru_cache(maxsize=None)
def _compiled(name, frozen_params, dyn_names, donate):
    op = _OPS[name]
    params = {k: v for k, v in frozen_params}

    def fn(*arrays, **dyn):
        return op.fn(*arrays, **params, **dyn)

    if not supports_donation():
        donate = ()
    return jax.jit(fn, donate_argnums=donate)


def _dyn_value(v):
    # Pass Python scalars through untouched: jit abstracts them as
    # WEAKLY-typed arrays, so bf16/fp16 arrays keep their dtype under
    # promotion (a strong f32 scalar would silently upcast fp16 weights
    # to f32 on the first optimizer step).
    return v


def split_params(op, params):
    """Split op params into (static, dyn, frozen_static) — dyn values are
    traced scalars (see Op.dynamic_params)."""
    dyn = {}
    static = {}
    for k, v in params.items():
        if v is None:
            continue
        if k in op.dynamic_params and isinstance(v, (int, float)) and \
                not isinstance(v, bool):
            dyn[k] = _dyn_value(v)
        else:
            static[k] = v
    frozen = tuple(sorted((k, _freeze(v)) for k, v in static.items()))
    return static, dyn, frozen


@functools.lru_cache(maxsize=None)
def vjp_jit(op_name, frozen_params, dyn_names, has_rng):
    """Cached jitted VJP for one op signature: (inputs, dyn, rng, cots) ->
    input cotangents.  The eager tape uses this so backward never
    re-traces/re-compiles an op it has differentiated before."""
    op = _OPS[op_name]
    params = {k: v for k, v in frozen_params}

    def run(inputs, dyn, rng, cots):
        def f(*arrs):
            if has_rng:
                out = op.fn(rng, *arrs, **params, **dyn)
            else:
                out = op.fn(*arrs, **params, **dyn)
            return out if isinstance(out, tuple) else (out,)
        _, vjp = jax.vjp(f, *inputs)
        return vjp(tuple(cots))

    return jax.jit(run)


def invoke(op, args, params, rng=None):
    """Eagerly invoke *op* on raw jax arrays, via the per-signature
    executable cache.  Returns a tuple of jax arrays."""
    if isinstance(op, str):
        op = get_op(op)
    static, dyn, frozen = split_params(op, params)
    # inputs spanning devices (model-parallel grads vs weights): move all
    # onto the first input's device — the reference's implicit
    # CopyFromTo at op boundaries (ndarray.cc:1184)
    devs = set()
    for a in args:
        if hasattr(a, "devices"):
            devs.update(a.devices())
    if len(devs) > 1:
        target = next(iter(args[0].devices()))
        args = [jax.device_put(a, target)
                if hasattr(a, "devices") and target not in a.devices()
                else a for a in args]
    donate = tuple(i + 1 for i in op.donate) if (op.needs_rng and op.donate) \
        else op.donate
    fn = _compiled(op.name, frozen, tuple(sorted(dyn)), donate)
    from .. import profiler as _prof
    _prof.bump_counter("eager_dispatches")
    profiling = _prof.is_running() and \
        _prof._config["profile_imperative"]
    t0 = _time.perf_counter() if profiling else 0.0
    if op.needs_rng:
        if rng is None:
            from ..runtime import rng as _rng
            rng = _rng.next_key()
        out = fn(rng, *args, **dyn)
    else:
        out = fn(*args, **dyn)
    if not isinstance(out, tuple):
        out = (out,)
    if profiling:
        # block so the span is real execution, not async dispatch
        # (the reference profiles the engine worker for the same reason)
        jax.block_until_ready(out)
        _prof.record_span(op.name, "operator", t0, _time.perf_counter())
    return out
