"""Reduction operators (reference: src/operator/tensor/broadcast_reduce_op.h).

mxnet reduction semantics: ``axis=None`` reduces all; ``exclude=True``
reduces over every axis *not* listed; ``keepdims`` keeps reduced dims as 1.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op, alias


def _axes(x, axis, exclude):
    if axis is None:
        ax = tuple(range(x.ndim))
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    ax = tuple(a % x.ndim for a in ax)
    if exclude:
        ax = tuple(a for a in range(x.ndim) if a not in ax)
    return ax


def _make(jfn, name, **extra):
    def f(x, axis=None, keepdims=False, exclude=False, **kw):
        return jfn(x, axis=_axes(x, axis, exclude), keepdims=keepdims)
    f.__name__ = name
    register_op(name)(f)
    return f


_make(jnp.sum, "sum")
alias("sum_axis", "sum")
_make(jnp.mean, "mean")
alias("mean_axis", "mean")
_make(jnp.prod, "prod")
_make(jnp.max, "max")
alias("max_axis", "max")
_make(jnp.min, "min")
alias("min_axis", "min")


@register_op("nansum")
def _nansum(x, axis=None, keepdims=False, exclude=False):
    return jnp.nansum(x, axis=_axes(x, axis, exclude), keepdims=keepdims)


@register_op("nanprod")
def _nanprod(x, axis=None, keepdims=False, exclude=False):
    return jnp.nanprod(x, axis=_axes(x, axis, exclude), keepdims=keepdims)


@register_op("logsumexp")
def _logsumexp(x, axis=None, keepdims=False, exclude=False):
    import jax
    return jax.scipy.special.logsumexp(x, axis=_axes(x, axis, exclude),
                                       keepdims=keepdims)


@register_op("L2Normalization")
def _l2_normalization(x, eps=1e-10, mode="instance"):
    # reference: src/operator/l2_normalization-inl.h
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
    else:
        raise ValueError(mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm
