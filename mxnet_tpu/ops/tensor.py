"""Shape-manipulation, indexing, init, ordering and linalg ops.

Covers the reference's ``src/operator/tensor/matrix_op*.cc`` (reshape,
transpose, slice, concat, ...), ``indexing_op.h`` (take, embedding,
gather_nd, one_hot), ``init_op.h`` (zeros/ones/arange), ``ordering_op``
(topk/sort/argsort), ``dot-inl.h`` and ``la_op.cc``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register_op, alias
from ..base import np_dtype
from ._precision import matmul_precision

# ---------------------------------------------------------------------------
# init ops (no array inputs; shape/ctx/dtype come as params)
# ---------------------------------------------------------------------------


@register_op("_zeros")
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, np_dtype(dtype))


@register_op("_ones")
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, np_dtype(dtype))


@register_op("_full")
def _full(shape=(), dtype="float32", value=0.0):
    return jnp.full(shape, value, np_dtype(dtype))


@register_op("_arange")
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register_op("_eye")
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) or None, k=int(k), dtype=np_dtype(dtype))


@register_op("_linspace")
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=np_dtype(dtype))


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------


def _infer_reshape(src_shape, spec, reverse=False):
    """Implements the reference's Reshape spec codes 0/-1/-2/-3/-4
    (src/operator/tensor/matrix_op-inl.h InferReshapeShape)."""
    spec = list(spec)
    src = list(src_shape)
    if reverse:
        spec = spec[::-1]
        src = src[::-1]
    out = []
    i = 0  # position in src
    j = 0
    while j < len(spec):
        s = spec[j]
        if s == 0:        # copy this dim
            out.append(src[i]); i += 1
        elif s == -1:     # infer
            out.append(-1); i += 1
        elif s == -2:     # copy all remaining
            out.extend(src[i:]); i = len(src)
        elif s == -3:     # merge two dims
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:     # split dim into next two spec entries
            d1, d2 = spec[j + 1], spec[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(int(s))
            if i < len(src):
                i += 1
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // known
    if reverse:
        out = out[::-1]
    return tuple(out)


@register_op("Reshape", aliases=("reshape",))
def _reshape(x, shape=(), reverse=False):
    return jnp.reshape(x, _infer_reshape(x.shape, shape, reverse))


@register_op("reshape_like")
def _reshape_like(x, y):
    return jnp.reshape(x, y.shape)


@register_op("Flatten", aliases=("flatten",))
def _flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register_op("transpose")
def _transpose(x, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(x, axes)


@register_op("SwapAxis", aliases=("swapaxes",))
def _swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register_op("expand_dims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register_op("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@register_op("broadcast_to")
def _broadcast_to(x, shape=()):
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, shape))
    return jnp.broadcast_to(x, tgt)


@register_op("broadcast_like")
def _broadcast_like(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(x, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register_op("Concat", aliases=("concat",))
def _concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register_op("stack")
def _stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


def _split_nout(params):
    return int(params.get("num_outputs", 1))


@register_op("SliceChannel", num_outputs=_split_nout, aliases=("split",))
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register_op("slice")
def _slice(x, begin=(), end=(), step=()):
    idx = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register_op("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register_op("slice_like")
def _slice_like(x, y, axes=()):
    idx = [slice(None)] * x.ndim
    axes = axes or range(x.ndim)
    for a in axes:
        idx[a] = slice(0, y.shape[a])
    return x[tuple(idx)]


@register_op("tile")
def _tile(x, reps=()):
    return jnp.tile(x, reps)


@register_op("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("Pad", aliases=("pad",))
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(x.ndim)]
    if mode == "constant":
        return jnp.pad(x, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise ValueError("unknown pad mode %r" % mode)


@register_op("reverse", aliases=("flip",))
def _reverse(x, axis=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axes)


@register_op("space_to_depth")
def _space_to_depth(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("depth_to_space")
def _depth_to_space(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


@register_op("take")
def _take(a, indices, axis=0, mode="clip"):
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=mode)


@register_op("pick")
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis)


@register_op("batch_take")
def _batch_take(a, indices):
    flat = a.reshape(-1)
    offs = jnp.arange(a.shape[0]) * a.shape[1]
    return flat[indices.astype(jnp.int32) + offs]


@register_op("Embedding")
def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
               sparse_grad=False):
    from .sparse_graph import SparseGradWeight
    if isinstance(weight, SparseGradWeight):
        # sparse_grad train path (see sparse_graph module docstring):
        # the vjp flows ONLY through the per-occurrence vals, so the
        # weight gradient is delivered as row_sparse pairs and no
        # (vocab, dim) dense cotangent exists in the backward program
        rows = jnp.take(jax.lax.stop_gradient(weight.weight),
                        data.astype(jnp.int32), axis=0)
        return rows + weight.vals
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register_op("one_hot")
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    return jax.nn.one_hot(indices.astype(jnp.int32), int(depth),
                          dtype=np_dtype(dtype)) * (on_value - off_value) \
        + off_value


@register_op("gather_nd")
def _gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register_op("scatter_nd")
def _scatter_nd(data, indices, shape=()):
    out = jnp.zeros(shape, data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register_op("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


@register_op("where")
def _where(cond, x, y):
    return jnp.where(cond != 0, x, y)


@register_op("SequenceMask", input_names=("data", "sequence_length"))
def _sequence_mask(data, *rest, use_sequence_length=False, value=0.0, axis=0):
    # data layout: (seq, batch, ...) when axis==0 (reference:
    # src/operator/sequence_mask-inl.h)
    if not use_sequence_length or not rest:
        return data
    seq_len = rest[0]
    steps = jnp.arange(data.shape[axis])
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    steps = steps.reshape(bshape)
    lshape = [1] * data.ndim
    lshape[1 - axis] = data.shape[1 - axis]
    mask = steps < seq_len.reshape(lshape)
    return jnp.where(mask, data, value)


@register_op("SequenceLast", input_names=("data", "sequence_length"))
def _sequence_last(data, *rest, use_sequence_length=False, axis=0):
    if not use_sequence_length or not rest:
        idx = data.shape[axis] - 1
        return jnp.take(data, idx, axis=axis)
    seq_len = rest[0].astype(jnp.int32)
    idx = seq_len - 1
    data_m = jnp.moveaxis(data, axis, 0)
    batch = jnp.arange(data_m.shape[1])
    return data_m[idx, batch]


@register_op("SequenceReverse", input_names=("data", "sequence_length"))
def _sequence_reverse(data, *rest, use_sequence_length=False, axis=0):
    if not use_sequence_length or not rest:
        return jnp.flip(data, axis)
    seq_len = rest[0].astype(jnp.int32)
    T = data.shape[axis]
    data_m = jnp.moveaxis(data, axis, 0)
    steps = jnp.arange(T)[:, None]
    rev_idx = jnp.where(steps < seq_len[None, :], seq_len[None, :] - 1 - steps,
                        steps)
    batch = jnp.arange(data_m.shape[1])[None, :]
    out = data_m[rev_idx, batch]
    return jnp.moveaxis(out, 0, axis)


# ---------------------------------------------------------------------------
# ordering (reference: src/operator/tensor/ordering_op-inl.h)
# ---------------------------------------------------------------------------


@register_op("sort")
def _sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register_op("argsort")
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(np_dtype(dtype))


def _topk_nout(params):
    ret = params.get("ret_typ", "indices")
    return 2 if ret == "both" else 1


@register_op("topk", num_outputs=_topk_nout)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False,
          dtype="float32"):
    k = int(k)
    if k <= 0:
        k = x.shape[axis]
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(jnp.negative(xm) if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(np_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        mask = jnp.zeros(jnp.moveaxis(x, axis, -1).shape, x.dtype)
        mask = mask.at[..., :].set(0)
        onehot = jax.nn.one_hot(jnp.moveaxis(idx, axis, -1).astype(jnp.int32),
                                x.shape[axis], dtype=x.dtype).sum(-2)
        return jnp.moveaxis(onehot, -1, axis)
    raise ValueError(ret_typ)


@register_op("argmax")
def _argmax(x, axis=None, keepdims=False):
    return jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmin")
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmax_channel")
def _argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


@register_op("shuffle", needs_rng=True, aliases=("_shuffle",))
def _shuffle(rng, x):
    return jax.random.permutation(rng, x, axis=0)


# ---------------------------------------------------------------------------
# dot / linalg (reference: dot-inl.h, la_op.cc)
# ---------------------------------------------------------------------------


def _dense_dot(a, b, transpose_a, transpose_b):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    prec = matmul_precision(a.dtype, b.dtype)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b, precision=prec)
    # mxnet dot contracts last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]), precision=prec)


@register_op("dot")
def _dot(a, b, transpose_a=False, transpose_b=False):
    from .sparse_graph import dense_dot_maybe_sparse
    return dense_dot_maybe_sparse(a, b, transpose_a, transpose_b,
                                  _dense_dot)


@register_op("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, precision=matmul_precision(a.dtype, b.dtype))


@register_op("khatri_rao")
def _khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out


@register_op("_linalg_gemm2", aliases=("linalg_gemm2",))
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0,
                  axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b,
                              precision=matmul_precision(a.dtype, b.dtype))


@register_op("_linalg_gemm", aliases=("linalg_gemm",))
def _linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b,
                              precision=matmul_precision(a.dtype, b.dtype)) + beta * c


@register_op("_linalg_potrf", aliases=("linalg_potrf",))
def _linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register_op("_linalg_potri", aliases=("linalg_potri",))
def _linalg_potri(a):
    l_inv = jax.scipy.linalg.solve_triangular(
        a, jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape),
        lower=True)
    return jnp.matmul(jnp.swapaxes(l_inv, -1, -2), l_inv,
                      precision=matmul_precision(a.dtype, a.dtype))


@register_op("_linalg_trmm", aliases=("linalg_trmm",))
def _linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    t = jnp.swapaxes(a, -1, -2) if transpose else a
    prec = matmul_precision(a.dtype, b.dtype)
    out = jnp.matmul(b, t, precision=prec) if rightside \
        else jnp.matmul(t, b, precision=prec)
    return alpha * out


@register_op("_linalg_trsm", aliases=("linalg_trsm",))
def _linalg_trsm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    if rightside:
        # solve X A = alpha B  <=>  A^T X^T = alpha B^T
        sol = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(sol, -1, -2)
    return jax.scipy.linalg.solve_triangular(
        a, alpha * b, lower=lower, trans=1 if transpose else 0)


@register_op("_linalg_syrk", aliases=("linalg_syrk",))
def _linalg_syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    prec = matmul_precision(a.dtype, a.dtype)
    if transpose:
        return alpha * jnp.matmul(at, a, precision=prec)
    return alpha * jnp.matmul(a, at, precision=prec)


@register_op("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def _linalg_sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register_op("_linalg_syevd", num_outputs=2, aliases=("linalg_syevd",))
def _linalg_syevd(a):
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register_op("_linalg_gelqf", num_outputs=2, aliases=("linalg_gelqf",))
def _linalg_gelqf(a):
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


@register_op("diag")
def _diag(x, k=0, axis1=0, axis2=1):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)


@register_op("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    if axis is None:
        v = jnp.sqrt(jnp.sum(jnp.square(x))) if ord == 2 \
            else jnp.sum(jnp.abs(x))
        return v.reshape((1,) * 0 + ()) if not keepdims else v.reshape(
            (1,) * x.ndim)
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


@register_op("ravel_multi_index", aliases=("_ravel_multi_index",))
def _ravel_multi_index(data, shape=()):
    idx = data.astype(jnp.int32)
    out = jnp.zeros(data.shape[1:], jnp.int32)
    for i, s in enumerate(shape):
        out = out * s + idx[i]
    return out.astype(jnp.float32)


@register_op("unravel_index", aliases=("_unravel_index",))
def _unravel_index(data, shape=()):
    idx = data.astype(jnp.int32)
    outs = []
    for s in reversed(shape):
        outs.append(idx % s)
        idx = idx // s
    return jnp.stack(outs[::-1], axis=0).astype(jnp.float32)


@register_op("histogram", num_outputs=2, aliases=("_histogram",))
def _histogram(data, bin_cnt=10, range=None):
    if range is not None:
        lo, hi = range
        edges = jnp.linspace(lo, hi, int(bin_cnt) + 1)
    else:
        edges = jnp.linspace(data.min(), data.max(), int(bin_cnt) + 1)
    idx = jnp.clip(jnp.searchsorted(edges, data.reshape(-1), side="right") - 1,
                   0, int(bin_cnt) - 1)
    in_range = ((data.reshape(-1) >= edges[0]) &
                (data.reshape(-1) <= edges[-1]))
    hist = jnp.zeros((int(bin_cnt),), jnp.float32).at[idx].add(
        in_range.astype(jnp.float32))
    return hist, edges.astype(jnp.float32)
