"""Operator library.

Importing this package registers every built-in operator into the registry
(``registry.py``), which the ``nd``/``sym`` front ends then expose as
generated functions — the in-process equivalent of the reference's op
reflection at import (``python/mxnet/base.py:578`` ``_init_op_module``).
"""

from .registry import (Op, register_op, get_op, list_ops, invoke,  # noqa
                       alias)
from . import elemwise      # noqa: F401
from . import tensor        # noqa: F401
from . import reduce        # noqa: F401
from . import nn            # noqa: F401
from . import random_ops    # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn           # noqa: F401
from . import control_flow  # noqa: F401
from . import quantization  # noqa: F401
from . import image         # noqa: F401
from . import detection     # noqa: F401
from . import spatial       # noqa: F401
from . import attention     # noqa: F401
from . import parity        # noqa: F401  (must come last: aliases)
