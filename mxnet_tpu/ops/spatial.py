"""Spatial-transform, correlation, deformable, and signal ops.

Reference coverage:
- SpatialTransformer / GridGenerator / BilinearSampler
  (``src/operator/spatial_transformer.cc``, ``grid_generator.cc``,
  ``bilinear_sampler.cc``)
- Correlation (``src/operator/correlation.cc``)
- Deformable convolution + PSROIPooling
  (``src/operator/contrib/deformable_convolution.cc``,
  ``psroi_pooling.cc``)
- SyncBatchNorm (``src/operator/contrib/sync_batch_norm.cc``)
- fft/ifft (``src/operator/contrib/fft.cc``), count_sketch
  (``count_sketch.cc``)

TPU-native notes: every sampler lowers to gathers + fused elementwise
math; Correlation and deformable conv unroll their (static, small)
displacement/kernel grids into shifted views XLA fuses into one kernel —
no scalar loops reach the device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ._precision import matmul_precision
from .registry import register_op


# ---------------------------------------------------------------------------
# bilinear sampling machinery
# ---------------------------------------------------------------------------


def _bilinear_sample_nchw(img, xs, ys):
    """Sample img (C, H, W) at float pixel coords xs/ys (...); zero
    padding outside (the reference BilinearSampler border behavior)."""
    C, H, W = img.shape
    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    x1 = x0 + 1
    y1 = y0 + 1
    wx1 = xs - x0
    wy1 = ys - y0

    def tap(xi, yi):
        valid = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        v = img[:, yc, xc]                    # (C, ...)
        return v * valid.astype(img.dtype)

    return (tap(x0, y0) * ((1 - wy1) * (1 - wx1)).astype(img.dtype)
            + tap(x1, y0) * ((1 - wy1) * wx1).astype(img.dtype)
            + tap(x0, y1) * (wy1 * (1 - wx1)).astype(img.dtype)
            + tap(x1, y1) * (wy1 * wx1).astype(img.dtype))


@register_op("BilinearSampler", input_names=("data", "grid"))
def _bilinear_sampler(data, grid):
    """data (N,C,H,W); grid (N,2,Ho,Wo) with normalized coords in
    [-1,1], grid[:,0]=x, grid[:,1]=y (reference: bilinear_sampler.cc)."""
    N, C, H, W = data.shape
    xs = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    ys = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return jax.vmap(_bilinear_sample_nchw)(data, xs, ys)


@register_op("GridGenerator", input_names=("data",))
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """affine: data (N,6) row-major 2x3 -> grid (N,2,H,W); warp: data is
    a flow field (N,2,H,W) added to the identity grid
    (reference: grid_generator.cc)."""
    th, tw = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        N = data.shape[0]
        ys, xs = jnp.meshgrid(
            jnp.linspace(-1.0, 1.0, th), jnp.linspace(-1.0, 1.0, tw),
            indexing="ij")
        ones = jnp.ones_like(xs)
        base = jnp.stack([xs, ys, ones], 0).reshape(3, -1)  # (3, H*W)
        theta = data.reshape(N, 2, 3)
        grid = theta @ base                                 # (N, 2, H*W)
        return grid.reshape(N, 2, th, tw)
    # warp: flow in pixels added to identity, then normalized
    N, _, H, W = data.shape
    ys, xs = jnp.meshgrid(jnp.arange(H, dtype=data.dtype),
                          jnp.arange(W, dtype=data.dtype), indexing="ij")
    x = xs[None] + data[:, 0]
    y = ys[None] + data[:, 1]
    xn = 2.0 * x / jnp.maximum(W - 1, 1) - 1.0
    yn = 2.0 * y / jnp.maximum(H - 1, 1) - 1.0
    return jnp.stack([xn, yn], 1)


@register_op("SpatialTransformer", input_names=("data", "loc"))
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine",
                         sampler_type="bilinear"):
    """Affine spatial transformer network op = GridGenerator +
    BilinearSampler (reference: spatial_transformer.cc)."""
    grid = _grid_generator(loc, "affine", target_shape)
    return _bilinear_sampler(data, grid)


# ---------------------------------------------------------------------------
# Correlation (FlowNet-style cost volume)
# ---------------------------------------------------------------------------


@register_op("Correlation", input_names=("data1", "data2"))
def _correlation(data1, data2, kernel_size=1, max_displacement=1,
                 stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """Cost volume between two feature maps (reference:
    correlation.cc).  Output (N, D*D, Ho, Wo) where D =
    2*(max_displacement//stride2)+1; each channel is the mean
    correlation at one displacement — the displacement grid is a static
    unrolled loop of shifted views, fused by XLA."""
    N, C, H, W = data1.shape
    pad = int(pad_size)
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    k = int(kernel_size)
    bk = k // 2
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    nd = md // s2
    D = 2 * nd + 1
    border = bk + md
    Hp, Wp = H + 2 * pad, W + 2 * pad
    ys = jnp.arange(border, Hp - border, s1)
    xs = jnp.arange(border, Wp - border, s1)
    outs = []
    norm = C * k * k
    for dy in range(-nd, nd + 1):
        for dx in range(-nd, nd + 1):
            acc = 0.0
            for ky in range(-bk, bk + 1):
                for kx in range(-bk, bk + 1):
                    p1 = d1[:, :, ys[:, None] + ky, xs[None, :] + kx]
                    p2 = d2[:, :, ys[:, None] + ky + dy * s2,
                            xs[None, :] + kx + dx * s2]
                    if is_multiply:
                        acc = acc + (p1 * p2).sum(axis=1)
                    else:
                        acc = acc + jnp.abs(p1 - p2).sum(axis=1)
            outs.append(acc / norm)
    return jnp.stack(outs, axis=1)


# ---------------------------------------------------------------------------
# deformable convolution + PSROIPooling
# ---------------------------------------------------------------------------


@register_op("_contrib_DeformableConvolution",
             input_names=("data", "offset", "weight", "bias"),
             aliases=("DeformableConvolution",))
def _deformable_conv(data, offset, weight, bias=None, kernel=(3, 3),
                     stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                     num_filter=0, num_group=1,
                     num_deformable_group=1, no_bias=False):
    """Deformable convolution v1 (reference:
    deformable_convolution.cc): each kernel tap samples the input at
    its base position plus a learned (dy, dx) offset via bilinear
    interpolation, then a 1x1-style contraction applies the weights.
    The kernel grid is static, so the tap loop unrolls into fused
    gathers."""
    N, C, H, W = data.shape
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    ph, pw = int(pad[0]), int(pad[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    ndg = int(num_deformable_group)
    # base sampling positions per output pixel
    ys = jnp.arange(Ho) * sh - ph
    xs = jnp.arange(Wo) * sw - pw
    cols = []        # one (N, C, Ho, Wo) sampled plane per kernel tap
    off = offset.reshape(N, ndg, kh * kw, 2, Ho, Wo)
    ch_per_dg = C // ndg
    for ki in range(kh):
        for kj in range(kw):
            tap = ki * kw + kj
            planes = []
            for g in range(ndg):
                dy = off[:, g, tap, 0]        # (N, Ho, Wo)
                dx = off[:, g, tap, 1]
                py = ys[None, :, None] + ki * dh + dy
                px = xs[None, None, :] + kj * dw + dx
                sub = data[:, g * ch_per_dg:(g + 1) * ch_per_dg]
                planes.append(jax.vmap(_bilinear_sample_nchw)(
                    sub, px, py))
            cols.append(jnp.concatenate(planes, axis=1))
    col = jnp.stack(cols, axis=2)   # (N, C, K, Ho, Wo)
    w = weight.reshape(int(num_filter), -1)   # (F, C/g * kh * kw)
    G = int(num_group)
    cpg = C // G
    fpg = int(num_filter) // G
    outs = []
    for g in range(G):
        colg = col[:, g * cpg:(g + 1) * cpg].reshape(
            N, cpg * kh * kw, Ho * Wo)
        wg = w[g * fpg:(g + 1) * fpg]
        outs.append(jnp.einsum(
            "fk,nkp->nfp", wg, colg,
            precision=matmul_precision(wg.dtype, colg.dtype)))
    out = jnp.concatenate(outs, axis=1).reshape(N, int(num_filter),
                                                Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register_op("_contrib_PSROIPooling", input_names=("data", "rois"),
             aliases=("PSROIPooling",))
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                   pooled_size=0, group_size=0):
    """Position-sensitive ROI pooling (reference: psroi_pooling.cc —
    R-FCN): output channel c's bin (i, j) average-pools input channel
    c * g^2 + i * g + j inside that bin."""
    g = int(group_size) if group_size else int(pooled_size)
    k = int(pooled_size)
    od = int(output_dim)
    N, C, H, W = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / k
        bin_w = rw / k
        img = data[bidx]
        # average via masked sum over the full map (static shapes)
        ys = jnp.arange(H, dtype=data.dtype) + 0.5
        xs = jnp.arange(W, dtype=data.dtype) + 0.5
        out = jnp.zeros((od, k, k), data.dtype)
        for i in range(k):
            for j in range(k):
                y_lo = y1 + i * bin_h
                y_hi = y1 + (i + 1) * bin_h
                x_lo = x1 + j * bin_w
                x_hi = x1 + (j + 1) * bin_w
                mask = ((ys[:, None] >= jnp.floor(y_lo)) &
                        (ys[:, None] < jnp.ceil(y_hi)) &
                        (xs[None, :] >= jnp.floor(x_lo)) &
                        (xs[None, :] < jnp.ceil(x_hi)))
                cnt = jnp.maximum(mask.sum(), 1)
                gi = i * g // k
                gj = j * g // k
                chans = img[(jnp.arange(od) * g + gi) * g + gj]
                val = (chans * mask[None]).sum((1, 2)) / cnt
                out = out.at[:, i, j].set(val)
        return out

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# SyncBatchNorm
# ---------------------------------------------------------------------------


@register_op("_contrib_SyncBatchNorm", num_outputs=5,
             num_visible_outputs=1,
             input_names=("data", "gamma", "beta", "moving_mean",
                          "moving_var"),
             aliases=("SyncBatchNorm",))
def _sync_batch_norm(data, gamma, beta, moving_mean, moving_var,
                     eps=1e-3, momentum=0.9, fix_gamma=True,
                     use_global_stats=False, output_mean_var=False,
                     ndev=1, key="", training=True):
    """Cross-device BatchNorm (reference: sync_batch_norm.cc, which
    runs a key-based global barrier + allreduce of the batch moments).

    TPU-native: under pjit the whole (global) batch is visible to one
    XLA program, so plain batch statistics ARE the synchronized
    statistics — XLA inserts the psum over the dp mesh axis when the
    batch dim is sharded.  The op therefore shares the BatchNorm math;
    ``ndev``/``key`` exist for API parity and are not needed."""
    from .nn import _batch_norm
    return _batch_norm(data, gamma, beta, moving_mean, moving_var,
                       eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                       use_global_stats=use_global_stats,
                       output_mean_var=output_mean_var,
                       axis=1, training=training)


from .registry import get_op as _get_op  # noqa: E402

# moving stats are mutable aux states mapped to the trailing outputs,
# exactly like BatchNorm
_get_op("_contrib_SyncBatchNorm").aux_states = {3: 3, 4: 4}


# ---------------------------------------------------------------------------
# fft / ifft / count_sketch
# ---------------------------------------------------------------------------


@register_op("_contrib_fft", aliases=("fft",))
def _fft(data, compute_size=128):
    """FFT over the last axis, output interleaved [re, im] pairs making
    the last dim 2x (reference: fft.cc output layout)."""
    f = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register_op("_contrib_ifft", aliases=("ifft",))
def _ifft(data, compute_size=128):
    """Inverse of _contrib_fft: interleaved (..., 2n) -> real (..., n).
    Matches the reference's unnormalized cuFFT inverse (scaled by n)."""
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * n


@register_op("_contrib_count_sketch", input_names=("data", "h", "s"),
             aliases=("count_sketch",))
def _count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count sketch projection (reference: count_sketch.cc): out[:, h[j]]
    += s[j] * data[:, j] — one scatter-add."""
    od = int(out_dim)
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    out = jnp.zeros(data.shape[:-1] + (od,), data.dtype)
    return out.at[..., idx].add(data * sign)


@register_op("_contrib_quadratic", aliases=("quadratic",))
def _quadratic(data, a=0.0, b=0.0, c=0.0):
    """Elementwise a*x^2 + b*x + c (reference:
    src/operator/contrib/quadratic_op-inl.h — the "how to add an
    operator" tutorial op)."""
    return a * data * data + b * data + c


@register_op("_contrib_index_copy", input_names=("old", "idx", "new"))
def _index_copy(old, idx, new):
    """Copy rows of *new* into *old* at positions *idx* (reference:
    src/operator/contrib/index_copy.cc).  Deviation: the reference
    bounds-checks and errors on out-of-range indices; under XLA a
    data-dependent error cannot be raised inside the compiled op, so
    out-of-range indices are DROPPED (no write) instead of silently
    clamping onto a wrong row."""
    return old.at[idx.astype(jnp.int32)].set(new, mode="drop")
