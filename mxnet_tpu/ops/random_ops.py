"""Random samplers (reference: src/operator/random/ — sample_op.cc etc.).

Every sampler takes a functional PRNG key as its first argument (supplied by
the runtime's key stream for eager calls, or an explicit key input for traced
graphs) — the TPU-native equivalent of the reference's kParallelRandom
resource (include/mxnet/resource.h:104).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from ..base import np_dtype


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register_op("_random_uniform", needs_rng=True, aliases=("uniform",))
def _uniform(rng, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(rng, _shape(shape), np_dtype(dtype), low, high)


@register_op("_random_normal", needs_rng=True,
             aliases=("normal", "_random_gaussian"))
def _normal(rng, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(rng, _shape(shape),
                                           np_dtype(dtype))


@register_op("_random_gamma", needs_rng=True, aliases=("gamma_sample",))
def _gamma(rng, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(rng, alpha, _shape(shape), np_dtype(dtype))


@register_op("_random_exponential", needs_rng=True)
def _exponential(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(rng, _shape(shape), np_dtype(dtype)) / lam


@register_op("_random_poisson", needs_rng=True)
def _poisson(rng, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(rng, lam, _shape(shape)).astype(np_dtype(dtype))


@register_op("_random_negative_binomial", needs_rng=True)
def _neg_binomial(rng, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register_op("_random_generalized_negative_binomial", needs_rng=True)
def _gen_neg_binomial(rng, mu=1.0, alpha=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register_op("_random_randint", needs_rng=True)
def _randint(rng, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(rng, _shape(shape), int(low), int(high),
                              np_dtype(dtype))


@register_op("_sample_uniform", needs_rng=True)
def _sample_uniform(rng, low, high, shape=(), dtype="float32"):
    s = _shape(shape)
    u = jax.random.uniform(rng, low.shape + s, np_dtype(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + \
        (high - low).reshape(low.shape + (1,) * len(s)) * u


@register_op("_sample_normal", needs_rng=True)
def _sample_normal(rng, mu, sigma, shape=(), dtype="float32"):
    s = _shape(shape)
    z = jax.random.normal(rng, mu.shape + s, np_dtype(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + \
        sigma.reshape(sigma.shape + (1,) * len(s)) * z


@register_op("_sample_gamma", needs_rng=True)
def _sample_gamma(rng, alpha, beta, shape=(), dtype="float32"):
    s = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(rng, jnp.broadcast_to(a, alpha.shape + s),
                         dtype=np_dtype(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register_op("_sample_multinomial", needs_rng=True,
             aliases=("sample_multinomial",))
def _sample_multinomial(rng, data, shape=(), get_prob=False, dtype="int32"):
    s = _shape(shape)
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-38))
    if data.ndim == 1:
        out = jax.random.categorical(rng, logits, shape=(n,))
        out = out.reshape(s) if s else out.reshape(())
    else:
        out = jax.random.categorical(rng, logits[:, None, :],
                                     axis=-1, shape=(data.shape[0], n))
        out = out.reshape((data.shape[0],) + s)
    return out.astype(np_dtype(dtype))


@register_op("_random_bernoulli", needs_rng=True)
def _bernoulli(rng, p=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(rng, p, _shape(shape)).astype(np_dtype(dtype))
