"""Graph-level sparse lowering — (values, indices[, indptr]) pairs
inside traced graphs (SURVEY §7 hard part (b)).

Reference: ``src/operator/tensor/cast_storage.cc:71`` +
``dot-inl.h`` sparse kernels behind storage-type inference
(``src/executor/infer_graph_attr_pass.cc``).

TPU-native design: XLA has no sparse tensors and jit needs static
shapes, so a sparse value crossing a traced graph is a registered
PYTREE carrier of dense component arrays.  ``jax.jit``/``jax.vjp``
treat the carrier as structure, ops dispatch on its type, and the
lowering is gather/segment_sum/scatter HLO — no dense projection of
the sparse operand is ever materialized:

* ``CsrCarrier`` — a CSR matrix bound as a graph input.  The executor
  builds one per ``CSRNDArray`` argument (executor.py ``_arg_map``);
  the ``dot`` op contracts it against dense right-hand sides via the
  same segment-sum lowering the eager layer uses (shared here), and
  ``cast_storage(stype='default')`` densifies it in-graph.
* ``SparseGradWeight`` — the Embedding ``sparse_grad=True`` path.  The
  executor's train step passes the weight wrapped with a zero
  per-occurrence perturbation ``vals``; the op computes
  ``take(stop_gradient(W), ids) + vals`` so the whole-graph vjp yields
  d(loss)/d(vals) — exactly the row_sparse gradient rows — while the
  stop_gradient guarantees NO dense (vocab, dim) cotangent exists
  anywhere in the backward program (the reference gets the same shape
  from SparseEmbedding's backward, indexing_op.cc).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["CsrCarrier", "SparseGradWeight", "csr_dot_dense",
           "dedup_rsp_pairs"]


@jax.tree_util.register_pytree_node_class
class CsrCarrier:
    """CSR components as one traced value: data/indices (nnz,),
    indptr (rows+1,), dense ``shape`` static."""

    def __init__(self, data, indices, indptr, shape):
        self.data = data
        self.indices = indices
        self.indptr = indptr
        self.shape = tuple(int(s) for s in shape)

    def tree_flatten(self):
        return (self.data, self.indices, self.indptr), self.shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        return cls(*children, shape)

    @property
    def dtype(self):
        return self.data.dtype

    def row_ids(self):
        """Row id per nnz entry, from indptr (static nnz)."""
        nnz = self.data.shape[0]
        return jnp.searchsorted(self.indptr.astype(jnp.int32),
                                jnp.arange(nnz), side="right") - 1

    def todense(self):
        out = jnp.zeros(self.shape, self.data.dtype)
        return out.at[self.row_ids(),
                      self.indices.astype(jnp.int32)].add(self.data)


@jax.tree_util.register_pytree_node_class
class SparseGradWeight:
    """Embedding weight + a zero per-occurrence perturbation whose
    cotangent IS the row_sparse gradient values (see module
    docstring)."""

    def __init__(self, weight, vals):
        self.weight = weight
        self.vals = vals

    def tree_flatten(self):
        return (self.weight, self.vals), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


def dedup_rsp_pairs(ids, vals, num_rows):
    """Canonicalize per-occurrence (ids, vals) pairs into sorted UNIQUE
    rows with summed values — jit-able at static shape.

    Row-wise optimizer kernels (lazy sgd/adagrad, sparse.py
    ``*_row_update``) use ``.at[rows].set`` and apply weight decay per
    listed row, so duplicate ids would corrupt their updates.  The
    output keeps the input's (n, dim) shape: slot i < num_unique holds
    a unique sorted id with its occurrences summed; the tail slots get
    id == num_rows — deliberately OUT OF BOUNDS, which jax scatter
    drops (and gather clamps, its result then dropped on write), so
    padding is a no-op for every .at[] consumer."""
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    n = flat_ids.shape[0]
    order = jnp.argsort(flat_ids)
    s_ids = flat_ids[order]
    s_vals = vals.reshape(n, -1)[order]
    is_new = jnp.concatenate([jnp.ones((1,), jnp.int32),
                              (s_ids[1:] != s_ids[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(is_new) - 1            # segment index per slot
    summed = jax.ops.segment_sum(s_vals, seg, num_segments=n)
    seg_ids = jnp.full((n,), num_rows, jnp.int32).at[seg].set(s_ids)
    return seg_ids, summed


def csr_dot_dense(csr, rhs, transpose_a=False):
    """csr × dense matmul by gather + segment-sum (transpose: scatter-
    add over columns) — the one lowering shared by the eager
    ``ndarray.sparse.dot`` and the graph-level ``dot`` op.  ``rhs`` may
    be 1-d or 2-d like the reference kernel (dot-inl.h csr paths)."""
    vals = csr.data
    cols = csr.indices.astype(jnp.int32)
    rhs2 = rhs.reshape(rhs.shape[0], -1)
    row_ids = csr.row_ids()
    if transpose_a:
        # out[col] += v * rhs[row]
        contrib = vals[:, None] * rhs2[row_ids]
        out = jnp.zeros((csr.shape[1], rhs2.shape[1]), vals.dtype)
        out = out.at[cols].add(contrib)
    else:
        gathered = vals[:, None] * rhs2[cols]
        out = jax.ops.segment_sum(gathered, row_ids,
                                  num_segments=csr.shape[0])
    if rhs.ndim == 1:
        return out.reshape(-1)
    return out


def dense_dot_maybe_sparse(a, b, transpose_a, transpose_b, dense_dot):
    """Dispatch helper for the registered ``dot`` op: route CSR
    carriers to the sparse lowering, everything else to ``dense_dot``.

    transpose_b on a CSR lhs and csr-rhs contraction fall back to
    densification — the reference's dot also densifies the pairs it
    has no sparse kernel for (dot-inl.h fallback)."""
    if isinstance(a, CsrCarrier):
        if isinstance(b, CsrCarrier):
            b = b.todense()
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return csr_dot_dense(a, b, transpose_a)
    if isinstance(b, CsrCarrier):
        b = b.todense()
    return dense_dot(a, b, transpose_a, transpose_b)
