"""Detection ops — SSD / RCNN family.

Reference capability: `src/operator/contrib/multibox_prior.cc`,
`multibox_target.cc`, `multibox_detection.cc`, `bounding_box.cc`
(box_nms/box_iou), `roi_align.cc`, `proposal.cc`.

TPU-first design: everything is fixed-shape, mask-based jnp.  The
reference's sequential kernels (bipartite matching, NMS suppression
loops) become `lax.fori_loop`s over static trip counts with boolean
masks — no dynamic shapes, so XLA compiles them into the surrounding
program; "removed" boxes are masked, not filtered.  Exact reference
tie-break semantics are kept where they are observable (stable score
ordering in NMS, first-match-wins bipartite matching).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _box_iou_corner(a, b):
    """IoU of (..., 4) corner boxes a[N,4] vs b[M,4] -> [N,M]."""
    al, at, ar, ab = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bl, bt, br, bb = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    iw = jnp.maximum(
        0.0, jnp.minimum(ar[:, None], br[None, :]) -
        jnp.maximum(al[:, None], bl[None, :]))
    ih = jnp.maximum(
        0.0, jnp.minimum(ab[:, None], bb[None, :]) -
        jnp.maximum(at[:, None], bt[None, :]))
    inter = iw * ih
    area_a = jnp.maximum(0.0, ar - al) * jnp.maximum(0.0, ab - at)
    area_b = jnp.maximum(0.0, br - bl) * jnp.maximum(0.0, bb - bt)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(boxes):
    x, y, w, h = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                  boxes[..., 3])
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                     axis=-1)


def _to_center(boxes):
    l, t, r, b = (boxes[..., 0], boxes[..., 1], boxes[..., 2],
                  boxes[..., 3])
    return jnp.stack([(l + r) / 2, (t + b) / 2, r - l, b - t], axis=-1)


# --------------------------------------------------------------------------
# MultiBoxPrior
# --------------------------------------------------------------------------

@register_op("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes from a feature map (reference:
    multibox_prior.cc MultiBoxPriorForward — first size with all ratios
    collapsed to [sizes... with ratio 1] + [ratios[1:] with sizes[0]]).
    data: (N, C, H, W); returns (1, H*W*A, 4) corner boxes."""
    in_h, in_w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w
    cy = (jnp.arange(in_h) + offsets[0]) * step_y
    cx = (jnp.arange(in_w) + offsets[1]) * step_x
    ws, hs = [], []
    for s in sizes:
        ws.append(s * in_h / in_w / 2)
        hs.append(s / 2)
    for r in ratios[1:]:
        sr = r ** 0.5
        ws.append(sizes[0] * in_h / in_w * sr / 2)
        hs.append(sizes[0] / sr / 2)
    ws = jnp.asarray(ws, data.dtype)
    hs = jnp.asarray(hs, data.dtype)
    cxg = jnp.broadcast_to(cx[None, :, None], (in_h, in_w, ws.size))
    cyg = jnp.broadcast_to(cy[:, None, None], (in_h, in_w, ws.size))
    out = jnp.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs], axis=-1)
    out = out.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out.astype(data.dtype)


# --------------------------------------------------------------------------
# MultiBoxTarget
# --------------------------------------------------------------------------

def _encode_loc(anchors, gt, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    return jnp.stack([
        (gx - ax) / aw / variances[0],
        (gy - ay) / ah / variances[1],
        jnp.log(jnp.maximum(gw / aw, 1e-12)) / variances[2],
        jnp.log(jnp.maximum(gh / ah, 1e-12)) / variances[3]], axis=-1)


@register_op("_contrib_MultiBoxTarget", num_outputs=3,
             aliases=("MultiBoxTarget",),
             input_names=("anchor", "label", "cls_pred"))
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference: multibox_target.cc
    MultiBoxTargetForward — greedy bipartite matching, then IoU-threshold
    matching, then hard-negative mining by background prob).

    anchor: (1, A, 4); label: (N, G, 5+) [cls, l, t, r, b]; cls_pred:
    (N, C, A).  Returns (loc_target (N, A*4), loc_mask (N, A*4),
    cls_target (N, A))."""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    G = label.shape[1]

    def one_batch(lbl, cpred):
        valid = lbl[:, 0] != -1.0
        iou = _box_iou_corner(anchors, lbl[:, 1:5])     # (A, G)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # greedy bipartite matching: G rounds of global argmax
        def bip_step(_, st):
            m_iou, m_gt, a_used, g_used = st
            masked = jnp.where(a_used[:, None] | g_used[None, :], -1.0,
                               iou)
            flat = jnp.argmax(masked)
            bi, bj = flat // G, flat % G
            best = masked[bi, bj]
            ok = best > 1e-6
            m_iou = m_iou.at[bi].set(jnp.where(ok, best, m_iou[bi]))
            m_gt = m_gt.at[bi].set(jnp.where(ok, bj, m_gt[bi]))
            a_used = a_used.at[bi].set(a_used[bi] | ok)
            g_used = g_used.at[bj].set(g_used[bj] | ok)
            return m_iou, m_gt, a_used, g_used

        m_iou = jnp.full((A,), -1.0, anchors.dtype)
        m_gt = jnp.full((A,), -1, jnp.int32)
        a_used = jnp.zeros((A,), bool)
        g_used = jnp.zeros((G,), bool)
        m_iou, m_gt, a_used, g_used = jax.lax.fori_loop(
            0, G, bip_step, (m_iou, m_gt, a_used, g_used))

        # threshold matching for remaining anchors
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        thr_pos = (~a_used) & (best_iou > overlap_threshold) \
            if overlap_threshold > 0 else jnp.zeros((A,), bool)
        m_gt = jnp.where(thr_pos, best_gt, m_gt)
        m_iou = jnp.where(a_used, m_iou, best_iou)
        positive = a_used | thr_pos
        num_pos = jnp.sum(positive)

        # negative selection
        if negative_mining_ratio > 0:
            num_neg = jnp.minimum(
                (num_pos * negative_mining_ratio).astype(jnp.int32),
                A - num_pos)
            num_neg = jnp.maximum(num_neg, minimum_negative_samples)
            # background prob of each anchor (class 0 row of cls_pred)
            logits = cpred                     # (C, A)
            mx = jnp.max(logits, axis=0)
            prob0 = jnp.exp(logits[0] - mx) / \
                jnp.sum(jnp.exp(logits - mx[None, :]), axis=0)
            cand = (~positive) & (m_iou < negative_mining_thresh)
            score = jnp.where(cand, prob0, jnp.inf)
            order = jnp.argsort(score, stable=True)   # hardest first
            rank = jnp.zeros((A,), jnp.int32).at[order].set(
                jnp.arange(A, dtype=jnp.int32))
            negative = cand & (rank < num_neg)
        else:
            negative = ~positive

        gt_boxes = lbl[jnp.maximum(m_gt, 0), 1:5]
        loc_t = _encode_loc(anchors, gt_boxes, variances)
        loc_t = jnp.where(positive[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(positive[:, None],
                          jnp.ones((A, 4), anchors.dtype),
                          0.0).reshape(-1)
        cls_t = jnp.where(
            positive, lbl[jnp.maximum(m_gt, 0), 0] + 1.0,
            jnp.where(negative, 0.0, ignore_label))
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_batch)(label, cls_pred)
    return loc_t, loc_m, cls_t


# --------------------------------------------------------------------------
# NMS (shared masked kernel)
# --------------------------------------------------------------------------

def _nms_mask(boxes, scores, valid, thresh, ids=None,
              force_suppress=True, topk=-1):
    """Greedy NMS keep-mask.  boxes (N,4) corner, scores desc-sortable.
    Returns keep mask in ORIGINAL order."""
    N = boxes.shape[0]
    order = jnp.argsort(-scores, stable=True)
    if topk > 0:
        in_topk = jnp.arange(N) < topk
    else:
        in_topk = jnp.ones((N,), bool)
    b = boxes[order]
    v = valid[order] & in_topk
    iou = _box_iou_corner(b, b)
    if ids is not None and not force_suppress:
        same = ids[order][:, None] == ids[order][None, :]
        iou = jnp.where(same, iou, 0.0)

    def step(i, keep):
        sup = jnp.any((iou[i] > thresh) & keep &
                      (jnp.arange(N) < i))
        return keep.at[i].set(v[i] & ~sup)

    keep_sorted = jax.lax.fori_loop(0, N, step, v)
    keep = jnp.zeros((N,), bool).at[order].set(keep_sorted)
    return keep


@register_op("_contrib_box_iou", aliases=("box_iou",),
             input_names=("lhs", "rhs"))
def _box_iou(lhs, rhs, format="corner"):
    if format == "center":
        lhs, rhs = _to_corner(lhs), _to_corner(rhs)
    lshape, rshape = lhs.shape[:-1], rhs.shape[:-1]
    out = _box_iou_corner(lhs.reshape(-1, 4), rhs.reshape(-1, 4))
    return out.reshape(lshape + rshape)


@register_op("_contrib_box_nms", num_outputs=2, num_visible_outputs=1,
             aliases=("box_nms",))
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1,
             force_suppress=False, in_format="corner",
             out_format="corner"):
    """Greedy NMS (reference: bounding_box.cc box_nms).  Suppressed and
    invalid entries become all -1 rows; survivors keep descending-score
    order.  data: (..., N, K)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])

    def one(batch):
        boxes = jax.lax.dynamic_slice_in_dim(batch, coord_start, 4,
                                             axis=1)
        if in_format == "center":
            boxes = _to_corner(boxes)
        scores = batch[:, score_index]
        valid = scores > valid_thresh
        ids = batch[:, id_index] if id_index >= 0 else None
        keep = _nms_mask(boxes, scores, valid, overlap_thresh, ids,
                         force_suppress or id_index < 0, topk)
        # survivors sorted by descending score, dead rows -1
        order = jnp.argsort(-jnp.where(keep, scores, -jnp.inf),
                            stable=True)
        rows = batch[order]
        if out_format != in_format:
            b = jax.lax.dynamic_slice_in_dim(rows, coord_start, 4,
                                             axis=1)
            b = _to_corner(b) if in_format == "center" else _to_center(b)
            rows = jax.lax.dynamic_update_slice_in_dim(
                rows, b, coord_start, axis=1)
        kept_sorted = keep[order]
        return jnp.where(kept_sorted[:, None], rows, -1.0)

    out = jax.vmap(one)(flat).reshape(shape)
    return out, out


# --------------------------------------------------------------------------
# MultiBoxDetection
# --------------------------------------------------------------------------

@register_op("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
             input_names=("cls_prob", "loc_pred", "anchor"))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0,
                        nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode SSD predictions + per-class NMS (reference:
    multibox_detection.cc).  cls_prob (N, C, A), loc_pred (N, A*4),
    anchor (1, A, 4) -> (N, A, 6) rows [cls_id, score, l, t, r, b],
    suppressed rows -1."""
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5

    def one(cprob, lpred):
        loc = lpred.reshape(A, 4)
        px = loc[:, 0] * variances[0] * aw + ax
        py = loc[:, 1] * variances[1] * ah + ay
        pw = jnp.exp(loc[:, 2] * variances[2]) * aw * 0.5
        ph = jnp.exp(loc[:, 3] * variances[3]) * ah * 0.5
        boxes = jnp.stack([px - pw, py - ph, px + pw, py + ph], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        scores = jnp.where(
            (jnp.arange(cprob.shape[0]) == background_id)[:, None],
            -1.0, cprob)
        cls_id = jnp.argmax(scores, axis=0)
        score = jnp.max(scores, axis=0)
        valid = score > threshold
        out_id = jnp.where(valid, cls_id.astype(cprob.dtype) -
                           (cls_id > background_id), -1.0)
        # reference maps class index skipping background: id-1 when
        # background_id==0
        keep = _nms_mask(boxes, score, valid, nms_threshold,
                         out_id, force_suppress, nms_topk)
        rows = jnp.concatenate(
            [out_id[:, None], score[:, None], boxes], axis=1)
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf),
                            stable=True)
        rows = rows[order]
        return jnp.where(keep[order][:, None], rows, -1.0)

    return jax.vmap(one)(cls_prob, loc_pred)


# --------------------------------------------------------------------------
# ROIAlign / ROIPooling-family + proposal
# --------------------------------------------------------------------------

@register_op("_contrib_ROIAlign", aliases=("ROIAlign",),
             input_names=("data", "rois"))
def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sample_ratio=-1):
    """ROI Align with bilinear sampling (reference: roi_align.cc,
    sampling grid per He et al. Mask R-CNN).  data (N,C,H,W), rois
    (R,5) [batch_idx, x1, y1, x2, y2] in image coords."""
    N, C, H, W = data.shape
    ph, pw = pooled_size
    sr = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        # sample grid: (ph*sr, pw*sr) bilinear points
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * rh / (ph * sr)
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * rw / (pw * sr)
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1)
        y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        wy1 = jnp.clip(ys - y0, 0.0, 1.0)
        wx1 = jnp.clip(xs - x0, 0.0, 1.0)
        img = data[bidx]                       # (C, H, W)
        v00 = img[:, y0i[:, None], x0i[None, :]]
        v01 = img[:, y0i[:, None], x1i[None, :]]
        v10 = img[:, y1i[:, None], x0i[None, :]]
        v11 = img[:, y1i[:, None], x1i[None, :]]
        val = (v00 * (1 - wy1)[None, :, None] * (1 - wx1)[None, None, :]
               + v01 * (1 - wy1)[None, :, None] * wx1[None, None, :]
               + v10 * wy1[None, :, None] * (1 - wx1)[None, None, :]
               + v11 * wy1[None, :, None] * wx1[None, None, :])
        # average the sr x sr samples per bin
        val = val.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))
        return val

    return jax.vmap(one_roi)(rois)


@register_op("_contrib_Proposal", aliases=("Proposal",),
             input_names=("cls_prob", "bbox_pred", "im_info"),
             num_outputs=lambda p: 2 if p.get("output_score") else 1)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposals (reference: proposal.cc): anchor decode + clip +
    min-size filter + NMS + top-k, masked fixed-shape output
    (rpn_post_nms_top_n rows per image)."""
    N, num_anchors2, H, W = cls_prob.shape
    A = num_anchors2 // 2
    base = feature_stride
    # base anchors at (0,0): all (scale, ratio) combos, centered
    ws, hs = [], []
    for r in ratios:
        size = base * base
        size_r = size / r
        w0 = round(size_r ** 0.5)
        h0 = round(w0 * r)
        for s in scales:
            ws.append(w0 * s)
            hs.append(h0 * s)
    ws = jnp.asarray(ws, cls_prob.dtype)
    hs = jnp.asarray(hs, cls_prob.dtype)
    cx = (base - 1) / 2.0
    shift_x = jnp.arange(W) * feature_stride
    shift_y = jnp.arange(H) * feature_stride
    acx = cx + shift_x[None, :, None]          # (1, W, A)
    acy = cx + shift_y[:, None, None]
    anchors = jnp.stack([
        jnp.broadcast_to(acx - (ws - 1) / 2, (H, W, A)),
        jnp.broadcast_to(acy - (hs - 1) / 2, (H, W, A)),
        jnp.broadcast_to(acx + (ws - 1) / 2, (H, W, A)),
        jnp.broadcast_to(acy + (hs - 1) / 2, (H, W, A))],
        axis=-1).reshape(-1, 4)                 # (H*W*A, 4)

    def one(cp, bp, info):
        scores = cp[A:].transpose(1, 2, 0).reshape(-1)   # fg scores
        deltas = bp.reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        ax = anchors[:, 0] + 0.5 * (aw - 1)
        ay = anchors[:, 1] + 0.5 * (ah - 1)
        px = deltas[:, 0] * aw + ax
        py = deltas[:, 1] * ah + ay
        pw = jnp.exp(deltas[:, 2]) * aw
        ph = jnp.exp(deltas[:, 3]) * ah
        boxes = jnp.stack([px - 0.5 * (pw - 1), py - 0.5 * (ph - 1),
                           px + 0.5 * (pw - 1), py + 0.5 * (ph - 1)],
                          axis=-1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=-1)
        min_size = rpn_min_size * info[2]
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
            ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        scores = jnp.where(keep_sz, scores, -jnp.inf)
        keep = _nms_mask(boxes, scores, keep_sz, threshold,
                         topk=rpn_pre_nms_top_n)
        order = jnp.argsort(-jnp.where(keep, scores, -jnp.inf),
                            stable=True)
        top = order[:rpn_post_nms_top_n]
        ok = keep[top]
        rois = jnp.where(ok[:, None], boxes[top], 0.0)
        sc = jnp.where(ok, scores[top], 0.0)
        return rois, sc

    rois, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(N, dtype=cls_prob.dtype),
                           rpn_post_nms_top_n)
    rois5 = jnp.concatenate(
        [batch_idx[:, None], rois.reshape(-1, 4)], axis=1)
    if output_score:
        return rois5, scores.reshape(-1, 1)
    return rois5
