"""Fused RNN operator — `jax.lax.scan` over time on the MXU.

Reference capability: the single fused multi-layer bidirectional RNN op
(`src/operator/rnn-inl.h:46-109` — kRnnRelu/kRnnTanh/kLstm/kGru — and its
cuDNN path `cudnn_rnn-inl.h`).  The TPU-native design replaces the cuDNN
descriptor machinery with one `lax.scan` per (layer, direction): the
per-step cell is a pair of MXU matmuls + elementwise gate math that XLA
fuses; the scan compiles to a single XLA While loop, so the whole
multi-layer stack is one program with no per-timestep dispatch.

Weight layout matches the reference's packed-vector convention
(`rnn-inl.h` GetParamSize): all weights first — per layer, per direction:
W_i2h (G*H, in), W_h2h (G*H, H) — then all biases in the same order:
b_i2h (G*H,), b_h2h (G*H,).  Gate order: LSTM i,f,g,o; GRU r,z,n
(`src/operator/rnn_impl.h`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional):
    """Total packed parameter count (reference: rnn-inl.h GetRnnParamSize)."""
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * g * state_size * (in_sz + state_size + 2)
    return size


def _unpack(params, mode, input_size, state_size, num_layers, bidirectional):
    """Slice the packed vector into per-(layer, dir) weight/bias arrays."""
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    h = state_size
    weights, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * dirs
        per_layer = []
        for _ in range(dirs):
            w_x = params[off:off + g * h * in_sz].reshape(g * h, in_sz)
            off += g * h * in_sz
            w_h = params[off:off + g * h * h].reshape(g * h, h)
            off += g * h * h
            per_layer.append((w_x, w_h))
        weights.append(per_layer)
    for layer in range(num_layers):
        per_layer = []
        for _ in range(dirs):
            b_x = params[off:off + g * h]
            off += g * h
            b_h = params[off:off + g * h]
            off += g * h
            per_layer.append((b_x, b_h))
        biases.append(per_layer)
    return weights, biases


def _scan_direction(mode, x_proj, w_h, b_h, h0, c0):
    """Scan one direction. x_proj: (T, B, G*H) input projections."""
    h = h0.shape[-1]

    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else \
            (lambda v: jnp.maximum(v, 0))

        def step(carry, xp):
            hy = carry[0]
            nh = act(xp + hy @ w_h.T + b_h)
            return (nh,), nh

        (hT,), out = jax.lax.scan(step, (h0,), x_proj)
        return out, hT, None

    if mode == "lstm":
        def step(carry, xp):
            hy, cy = carry
            pre = xp + hy @ w_h.T + b_h
            i, f, g, o = jnp.split(pre, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            nc = f * cy + i * g
            nh = o * jnp.tanh(nc)
            return (nh, nc), nh

        (hT, cT), out = jax.lax.scan(step, (h0, c0), x_proj)
        return out, hT, cT

    if mode == "gru":
        def step(carry, xp):
            hy = carry[0]
            rec = hy @ w_h.T + b_h
            xr, xz, xn = jnp.split(xp, 3, axis=-1)
            hr, hz, hn = jnp.split(rec, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            nh = (1 - z) * n + z * hy
            return (nh,), nh

        (hT,), out = jax.lax.scan(step, (h0,), x_proj)
        return out, hT, None

    raise ValueError("unknown RNN mode %r" % mode)


def _rnn_inputs(params):
    if params.get("mode", "lstm") == "lstm":
        return ("data", "parameters", "state", "state_cell")
    return ("data", "parameters", "state")


@register_op("RNN", needs_rng=True,
             input_names=("data", "parameters", "state", "state_cell"),
             num_outputs=lambda p: 3 if p.get("mode", "lstm") == "lstm"
                 else 2,
             num_visible_outputs=lambda p:
                 (3 if p.get("mode", "lstm") == "lstm" else 2)
                 if p.get("state_outputs") else 1)
def _rnn(rng, data, parameters, *rest, state_size=0, num_layers=1,
         bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
         lstm_state_clip_min=None, lstm_state_clip_max=None,
         lstm_state_clip_nan=False, training=True):
    """data: (T, B, input) sequence-major; optional state (L*dirs, B, H)
    and, for lstm, state_cell (zeros when omitted).
    Returns (output, hy[, cy])."""
    mode = str(mode)
    dirs = 2 if bidirectional else 1
    h = state_size
    in_size = data.shape[2]
    weights, biases = _unpack(parameters.astype(data.dtype), mode, in_size,
                              h, num_layers, bidirectional)
    sshape = (num_layers * dirs, data.shape[1], h)
    state = rest[0] if rest else jnp.zeros(sshape, data.dtype)
    if mode == "lstm":
        cell0 = rest[1] if len(rest) > 1 else jnp.zeros(sshape, data.dtype)
    else:
        cell0 = None

    x = data
    h_out, c_out = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            w_x, w_h = weights[layer][d]
            b_x, b_h = biases[layer][d]
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = cell0[idx] if cell0 is not None else None
            xs = jnp.flip(x, 0) if d == 1 else x
            # one big (T*B, in) @ (in, G*H) matmul outside the scan —
            # keeps the MXU busy with the large GEMM; only the (B, H)
            # recurrent GEMM remains sequential
            x_proj = xs @ w_x.T + b_x
            out, hT, cT = _scan_direction(mode, x_proj, w_h, b_h, h0, c0)
            if d == 1:
                out = jnp.flip(out, 0)
            outs.append(out)
            h_out.append(hT)
            if cT is not None:
                c_out.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if training and p > 0.0 and layer < num_layers - 1:
            keep = 1.0 - p
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), keep,
                x.shape).astype(x.dtype) / keep
            x = x * mask

    hy = jnp.stack(h_out, 0)
    if mode == "lstm":
        cy = jnp.stack(c_out, 0)
        if lstm_state_clip_min is not None and \
                lstm_state_clip_max is not None:
            if lstm_state_clip_nan:
                # reference semantics: NaN cell states are sanitized to
                # the clip bounds rather than propagated
                cy = jnp.nan_to_num(cy, nan=lstm_state_clip_max)
            cy = jnp.clip(cy, lstm_state_clip_min, lstm_state_clip_max)
        return x, hy, cy
    return x, hy


from .registry import get_op as _get_op  # noqa: E402

# non-LSTM modes consume no cell state; without this a symbolic
# sym.RNN(...) with 3 inputs would auto-create a phantom trainable
# "state_cell" variable (batch-size-dependent shape, saved to
# checkpoints) — same pattern as Convolution dropping "bias"
_get_op("RNN").active_inputs = _rnn_inputs
