"""Neural-network operators.

Covers the reference's ``src/operator/nn/`` family (Convolution,
Deconvolution, FullyConnected, BatchNorm, LayerNorm, Pooling, Activation,
softmax, Dropout, LRN, UpSampling — convolution-inl.h:58 etc.).  The whole
cuDNN/MKLDNN wrapper layer disappears: these lower directly to XLA HLO
(conv_general_dilated / reduce_window / dot_general hit the MXU natively).
Layout is NCHW at the API (reference default); XLA's layout assignment
re-tiles for the hardware, so no NHWC shim is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, alias
from ..base import np_dtype
from ._precision import matmul_precision

# ---------------------------------------------------------------------------
# FullyConnected / Activation / softmax
# ---------------------------------------------------------------------------


@register_op("FullyConnected", input_names=("data", "weight", "bias"))
def _fully_connected(data, weight, *rest, num_hidden=0, no_bias=False,
                     flatten=True):
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    # weight: (num_hidden, in_units) — contract on in_units (MXU matmul)
    out = jax.lax.dot_general(
        data, weight,
        dimension_numbers=(((data.ndim - 1,), (1,)), ((), ())),
        precision=matmul_precision(data.dtype, weight.dtype),
        preferred_element_type=jnp.float32 if data.dtype == jnp.bfloat16
        else None)
    if out.dtype != data.dtype:
        out = out.astype(data.dtype)
    if not no_bias and rest:
        out = out + rest[0]
    return out


@register_op("Activation")
def _activation(x, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(x, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "swish":
        return x * jax.nn.sigmoid(x)
    raise ValueError("unknown act_type %r" % act_type)


@register_op("softmax")
def _softmax(x, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def _log_softmax(x, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def _softmin(x, axis=-1, temperature=None):
    return jax.nn.softmax(-x, axis=axis)


@register_op("SoftmaxActivation")
def _softmax_activation(x, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


@register_op("SoftmaxOutput", aliases=("Softmax",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False,
                    preserve_shape=False, normalization="null",
                    out_grad=False, smooth_alpha=0.0):
    """Softmax forward with implicit cross-entropy backward.

    Reference: ``src/operator/softmax_output-inl.h`` — the backward pass
    ignores the incoming out_grad and emits (softmax - one_hot(label)),
    which we reproduce with ``jax.custom_vjp`` so both the eager tape and
    the fused graph executor see the same gradient.
    """
    if multi_output or (preserve_shape and data.ndim > 2):
        cls_axis = 1 if multi_output else data.ndim - 1
    else:
        cls_axis = data.ndim - 1
        if data.ndim > 2:
            data = data.reshape(data.shape[0], -1)
            cls_axis = 1

    n_class = data.shape[cls_axis]

    @jax.custom_vjp
    def f(d, l):
        return jax.nn.softmax(d, axis=cls_axis)

    def fwd(d, l):
        out = jax.nn.softmax(d, axis=cls_axis)
        return out, (out, l)

    def bwd(res, g):
        out, l = res
        li = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(li, n_class, dtype=out.dtype, axis=cls_axis)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / n_class
        grad = out - onehot
        if use_ignore:
            mask = (l != ignore_label).astype(out.dtype)
            grad = grad * jnp.expand_dims(mask, cls_axis)
        scale = grad_scale
        if normalization == "batch":
            scale = scale / grad.shape[0]
        elif normalization == "valid":
            if use_ignore:
                valid = jnp.maximum(jnp.sum(l != ignore_label), 1)
            else:
                valid = l.size
            scale = scale / valid
        grad = grad * scale
        return grad, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register_op("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    li = label.astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, li[:, None], axis=-1)
    return jnp.sum(nll)


@register_op("LinearRegressionOutput")
def _linear_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d * 1.0

    def fwd(d, l):
        return d * 1.0, (d, l)

    def bwd(res, g):
        d, l = res
        return grad_scale * (d - l.reshape(d.shape)), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register_op("MAERegressionOutput")
def _mae_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d * 1.0

    def fwd(d, l):
        return d * 1.0, (d, l)

    def bwd(res, g):
        d, l = res
        return grad_scale * jnp.sign(d - l.reshape(d.shape)), \
            jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register_op("LogisticRegressionOutput")
def _logistic_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return jax.nn.sigmoid(d)

    def fwd(d, l):
        return jax.nn.sigmoid(d), (jax.nn.sigmoid(d), l)

    def bwd(res, g):
        p, l = res
        return grad_scale * (p - l.reshape(p.shape)), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


# ---------------------------------------------------------------------------
# Convolution family
# ---------------------------------------------------------------------------


def _conv_dnums(nd):
    # NC + spatial; weights OI + spatial
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return jax.lax.conv_dimension_numbers((1, 1) + (1,) * nd,
                                          (1, 1) + (1,) * nd,
                                          (lhs, rhs, lhs))


def _tup(v, nd, default):
    if v is None or (isinstance(v, (tuple, list)) and len(v) == 0):
        return (default,) * nd
    if isinstance(v, int):
        return (v,) * nd
    return tuple(v)


@register_op("Convolution", input_names=("data", "weight", "bias"))
def _convolution(data, weight, *rest, kernel=(), stride=(), dilate=(),
                 pad=(), num_filter=0, num_group=1, workspace=1024,
                 no_bias=False, cudnn_tune=None, cudnn_off=False,
                 layout=None):
    nd = len(kernel)
    stride = _tup(stride, nd, 1)
    dilate = _tup(dilate, nd, 1)
    pad = _tup(pad, nd, 0)
    dn = _conv_dnums(nd)
    out = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group,
        precision=matmul_precision(data.dtype, weight.dtype))
    if not no_bias and rest:
        bias = rest[0]
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register_op("Deconvolution", input_names=("data", "weight", "bias"))
def _deconvolution(data, weight, *rest, kernel=(), stride=(), dilate=(),
                   pad=(), adj=(), target_shape=(), num_filter=0,
                   num_group=1, workspace=1024, no_bias=True,
                   cudnn_tune=None, cudnn_off=False, layout=None):
    # weight layout: (C_in, num_filter//num_group, *kernel) — reference
    # src/operator/nn/deconvolution-inl.h.  Implemented as the transpose
    # conv = lhs-dilated convolution with the flipped, IO-swapped kernel.
    nd = len(kernel)
    stride = _tup(stride, nd, 1)
    dilate = _tup(dilate, nd, 1)
    pad = _tup(pad, nd, 0)
    adj = _tup(adj, nd, 0)
    g = num_group
    cin = weight.shape[0]
    og = weight.shape[1]
    w = weight.reshape((g, cin // g, og) + tuple(kernel))
    w = jnp.swapaxes(w, 1, 2)                      # (g, og, cin//g, *k)
    w = w.reshape((g * og, cin // g) + tuple(kernel))
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    dn = _conv_dnums(nd)
    eff_k = tuple((kernel[i] - 1) * dilate[i] + 1 for i in range(nd))
    padding = [(eff_k[i] - 1 - pad[i], eff_k[i] - 1 - pad[i] + adj[i])
               for i in range(nd)]
    out = jax.lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=g,
        precision=matmul_precision(data.dtype, w.dtype))
    if not no_bias and rest:
        out = out + rest[0].reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


@register_op("Pooling")
def _pooling(data, kernel=(), pool_type="max", global_pool=False,
             cudnn_off=False, pooling_convention="valid", stride=(),
             pad=(), p_value=2, count_include_pad=True):
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            r = jnp.sum(data, axis=axes, keepdims=True)
            if pool_type == "avg":
                cnt = 1
                for a in axes:
                    cnt *= data.shape[a]
                r = r / cnt
            return r
        if pool_type == "lp":
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(data), p_value), axis=axes,
                        keepdims=True), 1.0 / p_value)
    kernel = _tup(kernel, nd, 1)
    stride = _tup(stride, nd, 1)
    pad = _tup(pad, nd, 0)

    def pads_for(i):
        lo = pad[i]
        hi = pad[i]
        if pooling_convention == "full":
            # ceil mode: add extra high padding so the last window fits
            size = data.shape[2 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem:
                hi += stride[i] - rem
        return (lo, hi)

    window = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple(pads_for(i) for i in range(nd))

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window,
                                     strides, pads)
    if pool_type in ("avg", "sum"):
        s = jax.lax.reduce_window(data, 0.0 if jnp.issubdtype(
            data.dtype, jnp.floating) else 0, jax.lax.add, window, strides,
            pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            cnt = 1
            for k in kernel:
                cnt *= k
            return s / cnt
        ones = jnp.ones_like(data)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads)
        return s / cnt
    if pool_type == "lp":
        s = jax.lax.reduce_window(jnp.power(jnp.abs(data), p_value), 0.0,
                                  jax.lax.add, window, strides, pads)
        return jnp.power(s, 1.0 / p_value)
    raise ValueError("unknown pool_type %r" % pool_type)


@register_op("ROIPooling")
def _roi_pooling(data, rois, pooled_size=(), spatial_scale=1.0):
    # reference: src/operator/roi_pooling-inl.h — max pool each scaled ROI
    # to a fixed (ph, pw) grid.  Batched over rois with vmap.
    ph, pw = pooled_size
    H, W = data.shape[2], data.shape[3]

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch_idx]                      # (C, H, W)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(iy, ix):
            hstart = y1 + (iy * rh) // ph
            hend = y1 + ((iy + 1) * rh + ph - 1) // ph
            wstart = x1 + (ix * rw) // pw
            wend = x1 + ((ix + 1) * rw + pw - 1) // pw
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(mask[None], img, -jnp.inf)
            v = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        iy, ix = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(iy, ix)   # (ph, pw, C)
        return jnp.transpose(cells, (2, 0, 1))

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------


@register_op("BatchNorm", num_outputs=5, num_visible_outputs=1)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=False,
                training=True):
    """Returns (out, batch_mean, batch_var, new_moving_mean, new_moving_var).

    The reference mutates aux states in the kernel
    (src/operator/nn/batch_norm-inl.h); functionally we return the updated
    moving stats and the caller rebinds them.
    """
    red = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    if training and not use_global_stats:
        # ONE pass over the activation: shifted sum and sum-of-squares
        # fuse into a single reduction (same input, two outputs), vs
        # mean+var's dependent two-pass form — BN inputs are the largest
        # tensors in a conv net, so the extra read is the expensive part.
        # The shift conditions the E[(x-c)^2]-(E[x-c])^2 identity: raw
        # E[x^2]-E[x]^2 cancels catastrophically when |mean| >> std.
        # c = one sampled element per channel is within O(std) of the
        # batch mean by construction (it IS a sample), so both terms
        # stay O(var) whatever the mean's magnitude — and unlike
        # moving_mean it cannot be stale.  f32 accumulation regardless
        # of a bf16 input: the cast fuses into the reduction read, and
        # bf16 accumulation over 1e6+ elements loses the statistics.
        n = 1
        for i in red:
            n *= data.shape[i]
        pick = tuple(0 if i in red else slice(None)
                     for i in range(data.ndim))
        c = jax.lax.stop_gradient(data[pick].astype(jnp.float32))
        xc = data.astype(jnp.float32) - c.reshape(bshape)
        s1 = jnp.sum(xc, axis=red)
        s2 = jnp.sum(xc * xc, axis=red)
        d1 = s1 / n
        mean = (c + d1).astype(moving_mean.dtype)
        var = jnp.maximum(s2 / n - jnp.square(d1), 0.0) \
            .astype(moving_var.dtype)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * \
        inv.reshape(bshape) * gamma.reshape(bshape).astype(data.dtype) + \
        beta.reshape(bshape).astype(data.dtype)
    return out, mean, var, new_mm, new_mv


@register_op("LayerNorm", num_outputs=3,
             num_visible_outputs=lambda p: 3 if p.get("output_mean_var") else 1)
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)


@register_op("InstanceNorm")
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * \
        gamma.reshape(bshape) + beta.reshape(bshape)


@register_op("LRN", num_outputs=2, num_visible_outputs=1)
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    # across-channel local response normalization (src/operator/nn/lrn.cc)
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) *
                     (data.ndim - 2))
    acc = jnp.zeros_like(data)
    for i in range(nsize):
        acc = acc + jax.lax.dynamic_slice_in_dim(padded, i, data.shape[1],
                                                 axis=1)
    norm = jnp.power(knorm + (alpha / nsize) * acc, -beta)
    return data * norm, norm


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------


@register_op("Dropout", num_outputs=2, needs_rng=True,
             num_visible_outputs=1)
def _dropout(rng, data, p=0.5, mode="training", axes=(), cudnn_off=False,
             training=True):
    if not training or mode == "always" and False:
        pass
    if (not training and mode != "always") or p == 0.0:
        return data, jnp.ones_like(data)
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, tuple(shape)).astype(data.dtype) \
        / keep
    return data * mask, jnp.broadcast_to(mask, data.shape)


# ---------------------------------------------------------------------------
# Resize / upsampling
# ---------------------------------------------------------------------------


@register_op("UpSampling")
def _upsampling(*args, scale=1, sample_type="nearest", num_args=1,
                num_filter=0, multi_input_mode="concat", workspace=512):
    data = args[0]
    if sample_type == "nearest":
        outs = []
        for d in args:
            o = jnp.repeat(jnp.repeat(d, scale, axis=2), scale, axis=3)
            outs.append(o)
        if len(outs) == 1:
            return outs[0]
        if multi_input_mode == "sum":
            out = outs[0]
            for o in outs[1:]:
                out = out + o
            return out
        return jnp.concatenate(outs, axis=1)
    # bilinear: weight is args[1] in the reference; use jax.image.resize
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), "bilinear")


@register_op("_contrib_BilinearResize2D")
def _bilinear_resize2d(data, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(h * scale_height)
        width = int(w * scale_width)
    return jax.image.resize(data, (n, c, int(height), int(width)),
                            "bilinear")


@register_op("_contrib_AdaptiveAvgPooling2D")
def _adaptive_avg_pool(data, output_size=()):
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    elif len(output_size) == 1:
        oh = ow = output_size[0]
    else:
        oh, ow = output_size
    n, c, h, w = data.shape
    # exact adaptive pooling: mean over variable windows; use resize-style
    # integral approach for the common divisible case, else interpolate
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), "linear")


@register_op("_contrib_div_sqrt_dim")
def _div_sqrt_dim(data):
    # reference: src/operator/contrib/transformer.cc:33
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


@register_op("BlockGrad", aliases=("stop_gradient",))
def _block_grad(x):
    return jax.lax.stop_gradient(x)


@register_op("make_loss", aliases=("MakeLoss",))
def _make_loss(x):
    return x * 1.0


def _custom_nout(params):
    from ..operator import get_prop
    prop = get_prop(params.get("op_type"))
    extra = {k: v for k, v in params.items() if k != "op_type"}
    return len(prop(**extra).list_outputs())


@register_op("Custom", num_outputs=_custom_nout)
def _custom(*inputs, op_type=None, **kwargs):
    """User Python op via the pure_callback bridge
    (see mxnet_tpu/operator.py; reference: src/operator/custom/)."""
    from ..operator import invoke_custom
    return invoke_custom(inputs, op_type, **kwargs)


# ---------------------------------------------------------------------------
# Losses as ops (reference keeps most losses in Gluon; ctc here)
# ---------------------------------------------------------------------------


@register_op("CTCLoss", aliases=("ctc_loss", "_contrib_CTCLoss",
                                 "_contrib_ctc_loss"),
             input_names=("data", "label", "data_lengths",
                          "label_lengths"))
def _ctc_loss(*inputs, use_data_lengths=False, use_label_lengths=False,
              blank_label="first"):
    """CTC loss. data: (seq, batch, alphabet) reference layout
    (src/operator/nn/ctc_loss.cc); lowered to optax.ctc_loss (blank=0).

    Like the reference op, the per-sequence length tensors are optional
    graph INPUTS gated by the use_*_lengths flags (active_inputs below),
    so padded activations/labels past each sequence's length are
    excluded from the alignment."""
    import optax
    expected = 2 + bool(use_data_lengths) + bool(use_label_lengths)
    if len(inputs) != expected:
        raise TypeError(
            "CTCLoss expects %d inputs for use_data_lengths=%r, "
            "use_label_lengths=%r; got %d"
            % (expected, use_data_lengths, use_label_lengths, len(inputs)))
    rest = list(inputs[2:])
    data_lengths = rest.pop(0) if use_data_lengths else None
    label_lengths = rest.pop(0) if use_label_lengths else None
    data, label = inputs[0], inputs[1]
    seq, batch, nalpha = data.shape
    logits = jnp.transpose(data, (1, 0, 2))          # (B, T, A)
    labels = label.astype(jnp.int32)
    if blank_label == "first":
        # optax uses blank=0 as well; labels in mxnet 'first' mode are
        # 1-based already
        pass
    else:
        # 'last': blank is alphabet-1; rotate so blank becomes 0
        logits = jnp.concatenate([logits[..., -1:], logits[..., :-1]], -1)
        labels = labels + 1
    if data_lengths is not None:
        t_idx = jnp.arange(seq)[None, :]
        logit_paddings = (t_idx >=
                          data_lengths.astype(jnp.int32).reshape(-1, 1)
                          ).astype(jnp.float32)
    else:
        logit_paddings = jnp.zeros((batch, seq), jnp.float32)
    if label_lengths is not None:
        l_idx = jnp.arange(labels.shape[1])[None, :]
        label_paddings = (l_idx >=
                          label_lengths.astype(jnp.int32).reshape(-1, 1)
                          ).astype(jnp.float32)
    else:
        lab_valid = (labels > 0).astype(jnp.float32)
        label_paddings = 1.0 - lab_valid
    loss = optax.ctc_loss(jax.nn.log_softmax(logits, -1), logit_paddings,
                          labels, label_paddings)
    return loss


def _ctc_inputs(params):
    names = ["data", "label"]
    if params.get("use_data_lengths", False):
        names.append("data_lengths")
    if params.get("use_label_lengths", False):
        names.append("label_lengths")
    return tuple(names)


# -- symbolic metadata -------------------------------------------------------
from .registry import get_op as _get_op

_bn = _get_op("BatchNorm")
_bn.aux_states = {3: 3, 4: 4}   # moving_mean, moving_var -> outputs 3, 4

def _conv_inputs(params):
    if params.get("no_bias", False):
        return ("data", "weight")
    return ("data", "weight", "bias")

_get_op("Convolution").active_inputs = _conv_inputs
_get_op("CTCLoss").active_inputs = _ctc_inputs
_get_op("FullyConnected").active_inputs = _conv_inputs

def _deconv_inputs(params):
    if params.get("no_bias", True):
        return ("data", "weight")
    return ("data", "weight", "bias")

_get_op("Deconvolution").active_inputs = _deconv_inputs


def top1_route(x, gate_weight, cap, precision=None):
    """Shared top-1 capacity routing: softmax router, argmax expert,
    1-based cumsum position within the expert's capacity buffer.
    Returns (probs, gate, expert_idx, slot, keep).  Used by the
    _contrib_MoEFFN op below and parallel/moe.py's shard_map variant —
    one definition so the two MoE paths cannot diverge."""
    e = gate_weight.shape[1]
    logits = jnp.einsum("nd,de->ne", x, gate_weight,
                        precision=precision)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot              # 1-based
    slot = jnp.sum(pos, axis=-1) - 1
    keep = slot < cap
    return probs, gate, expert_idx, slot, keep


@register_op("_contrib_MoEFFN", aliases=("MoEFFN",), num_outputs=2,
             num_visible_outputs=lambda p: 2
             if p.get("output_aux_loss") else 1)
def _moe_ffn(data, gate_weight, expert_w1, expert_w2,
             capacity_factor=1.0, act_type="relu",
             output_aux_loss=False):
    """Top-1 capacity-routed mixture-of-experts FFN, GShard einsum
    formulation (reference has no MoE; TPU extension alongside
    parallel/moe.py's explicit shard_map variant).

    data: (N, D) tokens; gate_weight: (D, E); expert_w1: (E, D, H);
    expert_w2: (E, H, D).  All routing/dispatch/combine are static-
    shape einsums over a (N, E, C) dispatch tensor, so the op traces
    like any other symbol op and — with the expert leaves sharded
    P('ep', ...) at trainer level — XLA's SPMD partitioner inserts the
    token all-to-alls itself; no shard_map or mesh plumbing in the op.
    Tokens beyond an expert's capacity C = ceil(cf * N / E) are dropped
    (standard top-1 semantics); combine carries the router probability
    so the gate learns.

    Outputs: out (N, D); with ``output_aux_loss=True`` also the
    load-balancing loss (mean fraction-routed x mean gate-prob per
    expert, scaled by E^2 — the GShard/Switch auxiliary) as a second
    visible output to add to the training loss.
    """
    n, dmodel = data.shape
    e = gate_weight.shape[1]
    cap = max(1, int(-(-float(capacity_factor) * n // e)))
    prec = matmul_precision(data.dtype, expert_w1.dtype)
    probs, gate, expert_idx, slot, keep = top1_route(
        data, gate_weight, cap, precision=prec)
    # dispatch: (N, E, C) one-hot of (expert, capacity slot)
    dispatch = (jax.nn.one_hot(expert_idx, e, dtype=data.dtype)[:, :, None]
                * jax.nn.one_hot(jnp.where(keep, slot, cap),
                                 cap + 1, dtype=data.dtype)[:, None, :cap])
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, data,
                           precision=prec)                 # (E, C, D)
    h = jnp.einsum("ecd,edh->ech", expert_in, expert_w1,
                   precision=prec)
    h = _activation(h, act_type=act_type)
    out_e = jnp.einsum("ech,ehd->ecd", h, expert_w2, precision=prec)
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum("nec,ecd->nd", combine, out_e, precision=prec)
    # load balancing (Switch aux): fraction routed x mean router prob.
    # Only visible with output_aux_loss=True (LayerNorm's
    # output_mean_var pattern) — add it to the training loss to avoid
    # expert collapse.
    frac = jnp.mean(jax.nn.one_hot(expert_idx, e, dtype=data.dtype),
                    axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    # Switch/GShard formulation: E * sum_e(frac_e * prob_e), i.e. the
    # MEAN over experts scaled by E^2 (== 1 at uniform routing); sum
    # would be E x too large
    aux = jnp.mean(frac * mean_prob) * (e * e)
    return out, aux.astype(data.dtype)
