"""Quantization operators.

Covers the reference's INT8 path (src/operator/quantization/: quantize,
dequantize, requantize) and KVStore's 2-bit gradient compression with
error-feedback residual (src/kvstore/gradient_compression.cc:60,101-113).
All pure jnp — the 2-bit pack runs as one fused XLA kernel per tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from ..base import np_dtype


@register_op("_contrib_quantize", num_outputs=3, aliases=("quantize",))
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize to int8/uint8 (reference: quantize-inl.h)."""
    if out_type == "uint8":
        qmin, qmax = 0.0, 255.0
        dt = jnp.uint8
    else:
        qmin, qmax = -127.0, 127.0
        dt = jnp.int8
    scale = (qmax - qmin) / jnp.maximum(max_range - min_range, 1e-20)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(dt), min_range, max_range


@register_op("_contrib_dequantize", aliases=("dequantize",))
def _dequantize(data, min_range, max_range, out_type="float32"):
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    elif data.dtype == jnp.int32:
        # int32 accumulator out of the quantized conv/fc ops
        qmin, qmax = -(2.0 ** 31 - 1), 2.0 ** 31 - 1
    else:
        qmin, qmax = -127.0, 127.0
    scale = (max_range - min_range) / (qmax - qmin)
    # affine as q*scale + offset, NOT (q - qmin)*scale + min: at int32
    # magnitudes (q - qmin) ~ 2^31 and float32's ~2^-24 relative
    # resolution wipes the accumulator's low bits (offset folds the
    # same constants with no precision loss; for symmetric ranges it
    # is exactly 0)
    return data.astype(jnp.float32) * scale + (min_range - qmin * scale)


@register_op("_contrib_requantize", num_outputs=3)
def _requantize(data, min_range, max_range, min_calib_range=None,
                max_calib_range=None):
    # int32 -> int8 with (possibly calibrated) range.  The int32
    # accumulator carries a SYMMETRIC real range (see _int32_range
    # below): real = q * MaxAbs(min, max) / (2^31-1) — the reference's
    # requantize-inl.h MaxAbs convention, and the same scale the int32
    # branch of _dequantize above resolves to for a symmetric range.
    real = data.astype(jnp.float32) * \
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / \
        (2.0 ** 31 - 1)
    lo = min_calib_range if min_calib_range is not None else min_range
    hi = max_calib_range if max_calib_range is not None else max_range
    scale = 127.0 / jnp.maximum(jnp.maximum(jnp.abs(lo), jnp.abs(hi)),
                                1e-20)
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, -jnp.abs(hi), jnp.abs(hi)


# ---------------------------------------------------------------------------
# INT8 compute ops — int8 x int8 -> int32 on the MXU
# (reference: src/operator/quantization/quantized_conv.cc,
# quantized_fully_connected.cc, quantized_pooling.cc,
# quantized_flatten.cc).  Convention: a quantized tensor carries a
# symmetric real range (min, max); real = q * M / 127 with
# M = max(|min|, |max|).  The int32 accumulator's range is therefore
# (2^31-1) * Md * Mw / 127^2, which is what dequantize below assumes.
# ---------------------------------------------------------------------------


def _sym_scale(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx)) / 127.0


def _int32_range(dmin, dmax, wmin, wmax):
    m = _sym_scale(dmin, dmax) * _sym_scale(wmin, wmax) * (2.0 ** 31 - 1)
    return -m, m


@register_op("_contrib_quantized_conv", num_outputs=3,
             aliases=("quantized_conv",))
def _quantized_conv(data, weight, dmin, dmax, wmin, wmax, kernel=(1, 1),
                    stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                    num_filter=0, num_group=1, no_bias=True,
                    layout="NCHW"):
    """int8 NCHW convolution with int32 accumulation (the MXU int8
    path; XLA lowers preferred_element_type=int32 onto the systolic
    array)."""
    nd_ = len(kernel)
    pads = [(int(p), int(p)) for p in pad] if pad else [(0, 0)] * nd_
    out = jax.lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=tuple(int(s) for s in stride),
        padding=pads,
        rhs_dilation=tuple(int(d) for d in dilate),
        feature_group_count=int(num_group),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    omin, omax = _int32_range(dmin, dmax, wmin, wmax)
    return out, omin, omax


@register_op("_contrib_quantized_fully_connected", num_outputs=3,
             aliases=("quantized_fc",))
def _quantized_fc(data, weight, dmin, dmax, wmin, wmax, num_hidden=0,
                  no_bias=True, flatten=True):
    d = data.astype(jnp.int8)
    if flatten and d.ndim > 2:
        d = d.reshape(d.shape[0], -1)
    out = jax.lax.dot_general(
        d, weight.astype(jnp.int8),
        (((d.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    omin, omax = _int32_range(dmin, dmax, wmin, wmax)
    return out, omin, omax


@register_op("_contrib_quantized_pooling", num_outputs=3,
             aliases=("quantized_pooling",))
def _quantized_pooling(data, dmin, dmax, kernel=(2, 2), stride=None,
                       pad=None, pool_type="max", global_pool=False):
    """int8 pooling: max stays exact in int8; avg accumulates in int32
    then rounds back (range is unchanged either way)."""
    d = data
    nd_ = len(kernel)
    if global_pool:
        kernel = d.shape[2:]
        stride = (1,) * nd_
        pad = (0,) * nd_
    stride = stride or kernel
    pad = pad or (0,) * nd_
    dims = (1, 1) + tuple(int(k) for k in kernel)
    strides = (1, 1) + tuple(int(s) for s in stride)
    pads = ((0, 0), (0, 0)) + tuple((int(p), int(p)) for p in pad)
    if pool_type == "max":
        # identity element in the INPUT's integer dtype: an int8 init
        # under a uint8 window is a dtype error, not a silent corner
        init = jnp.array(jnp.iinfo(d.dtype).min, d.dtype)
        out = jax.lax.reduce_window(d, init, jax.lax.max, dims,
                                    strides, pads)
    else:
        s = jax.lax.reduce_window(d.astype(jnp.int32), 0, jax.lax.add,
                                  dims, strides, pads)
        n = 1
        for k in kernel:
            n *= int(k)
        lo, hi = (0, 255) if d.dtype == jnp.uint8 else (-127, 127)
        out = jnp.clip(jnp.round(s / n), lo, hi).astype(d.dtype)
    return out, dmin, dmax


@register_op("_contrib_quantized_flatten", num_outputs=3,
             aliases=("quantized_flatten",))
def _quantized_flatten(data, dmin, dmax):
    return data.reshape(data.shape[0], -1), dmin, dmax


@register_op("_contrib_quantized_act", num_outputs=3,
             aliases=("quantized_act",))
def _quantized_act(data, dmin, dmax, act_type="relu"):
    """Activation that stays in the quantized domain (reference:
    quantized_activation.cc — relu-only, like the MKLDNN int8 path).

    With the symmetric convention (real = q * M / 127, M > 0) relu
    commutes with dequantization — max(q, 0) * s == max(q * s, 0) — so
    the output carries the input's range unchanged and no requantize
    is needed between a quantized conv/fc and its relu."""
    if act_type != "relu":
        raise ValueError("quantized activation supports act_type='relu' "
                         "only, got %r" % (act_type,))
    return jnp.maximum(data, jnp.array(0, data.dtype)), dmin, dmax


# ---------------------------------------------------------------------------
# 2-bit gradient compression (error feedback)
# ---------------------------------------------------------------------------


@register_op("_contrib_quantize_2bit", num_outputs=2)
def _quantize_2bit(grad, residual, threshold=0.5):
    """Ternarize grad+residual to {-t, 0, +t}; returns (codes, residual').

    codes: int8 in {-1, 0, 1} (the reference packs 16 values/word —
    src/kvstore/gradient_compression.cc Quantize2BitKernel; we keep int8
    lanes, the wire format packs separately).
    """
    acc = grad + residual
    pos = (acc >= threshold)
    neg = (acc <= -threshold)
    code = pos.astype(jnp.int8) - neg.astype(jnp.int8)
    decoded = code.astype(grad.dtype) * threshold
    new_residual = acc - decoded
    return code, new_residual


@register_op("_contrib_dequantize_2bit")
def _dequantize_2bit(codes, threshold=0.5, dtype="float32"):
    return codes.astype(np_dtype(dtype)) * threshold


def pack_2bit(codes):
    """Host-side: pack int8 {-1,0,1} lanes into a uint8 array, 4 values
    per byte (wire format for the dist kvstore)."""
    import numpy as np
    flat = np.asarray(codes).ravel()
    pad = (-len(flat)) % 4
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    two_bit = (flat + 1).astype(np.uint8)      # {-1,0,1} -> {0,1,2}
    packed = (two_bit[0::4] | (two_bit[1::4] << 2) |
              (two_bit[2::4] << 4) | (two_bit[3::4] << 6))
    return packed, len(np.asarray(codes).ravel())


def unpack_2bit(packed, n):
    import numpy as np
    packed = np.asarray(packed)
    vals = np.empty(len(packed) * 4, np.int8)
    for i in range(4):
        vals[i::4] = ((packed >> (2 * i)) & 0x3).astype(np.int8) - 1
    return vals[:n]
