"""Elementwise unary/binary/scalar operators.

Covers the reference's ``src/operator/tensor/elemwise_unary_op_basic.cc``,
``elemwise_binary_op_basic.cc``, ``elemwise_binary_broadcast_op_*.cc`` and
``elemwise_binary_scalar_op_*.cc`` families.  Every op is a pure jnp
expression — XLA fuses chains of these into single kernels, which is the
TPU-native version of the reference's expression-template fusion (mshadow).
"""

from __future__ import annotations

import jax.numpy as jnp
import jax

from .registry import register_op, alias

# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.fix,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "softrelu": jax.nn.softplus,
    "_copy": lambda x: x + 0,
    "identity": lambda x: x,
    "zeros_like": jnp.zeros_like,
    "ones_like": jnp.ones_like,
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
}

for _name, _f in _UNARY.items():
    register_op(_name)(_f)


@register_op("clip")
def _clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register_op("Cast", aliases=("cast",))
def _cast(x, dtype="float32"):
    from ..base import np_dtype
    return x.astype(np_dtype(dtype))


@register_op("LeakyReLU", input_names=("data", "gamma"))
def _leaky_relu(x, *rest, act_type="leaky", slope=0.25, lower_bound=0.125,
                upper_bound=0.334):
    # reference: src/operator/leaky_relu-inl.h (leaky/prelu/elu/selu/gelu,
    # rrelu uses the midpoint of [lower,upper] at inference)
    if act_type == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act_type == "prelu":
        gamma = rest[0]
        return jnp.where(x > 0, x, gamma * x)
    if act_type == "elu":
        return jnp.where(x > 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(x > 0, x, a * jnp.expm1(x))
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(x > 0, x, mid * x)
    raise ValueError("unknown LeakyReLU act_type %r" % act_type)


# ---------------------------------------------------------------------------
# binary (elemwise_* requires same shape; broadcast_* broadcasts — the
# reference keeps them separate ops, we keep the names but both broadcast)
# ---------------------------------------------------------------------------

def _logical(fn):
    def wrapped(a, b):
        return fn(a != 0, b != 0).astype(a.dtype)
    return wrapped


_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b).astype(a.dtype),
    "not_equal": lambda a, b: (a != b).astype(a.dtype),
    "greater": lambda a, b: (a > b).astype(a.dtype),
    "greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "lesser": lambda a, b: (a < b).astype(a.dtype),
    "lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "logical_and": _logical(jnp.logical_and),
    "logical_or": _logical(jnp.logical_or),
    "logical_xor": _logical(jnp.logical_xor),
}

for _name, _f in _BINARY.items():
    register_op("broadcast_" + _name)(_f)

for _name in ("add", "sub", "mul", "div"):
    alias("elemwise_" + _name, "broadcast_" + _name)
alias("_plus", "broadcast_add")
alias("_minus", "broadcast_sub")
alias("_mul", "broadcast_mul")
alias("_div", "broadcast_div")
alias("_mod", "broadcast_mod")
alias("_power", "broadcast_power")
alias("_maximum", "broadcast_maximum")
alias("_minimum", "broadcast_minimum")
alias("_hypot", "broadcast_hypot")
alias("_equal", "broadcast_equal")
alias("_not_equal", "broadcast_not_equal")
alias("_greater", "broadcast_greater")
alias("_greater_equal", "broadcast_greater_equal")
alias("_lesser", "broadcast_lesser")
alias("_lesser_equal", "broadcast_lesser_equal")


# ---------------------------------------------------------------------------
# scalar variants (reference: elemwise_binary_scalar_op files; internal
# _plus_scalar etc. names are what the front ends call)
# ---------------------------------------------------------------------------

_SCALAR = {
    "_plus_scalar": lambda x, scalar=0.0: x + scalar,
    "_minus_scalar": lambda x, scalar=0.0: x - scalar,
    "_rminus_scalar": lambda x, scalar=0.0: scalar - x,
    "_mul_scalar": lambda x, scalar=1.0: x * scalar,
    "_div_scalar": lambda x, scalar=1.0: x / scalar,
    "_rdiv_scalar": lambda x, scalar=1.0: scalar / x,
    "_mod_scalar": lambda x, scalar=1.0: jnp.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar=1.0: jnp.mod(scalar, x),
    "_power_scalar": lambda x, scalar=1.0: jnp.power(x, scalar),
    "_rpower_scalar": lambda x, scalar=1.0: jnp.power(scalar, x),
    "_maximum_scalar": lambda x, scalar=0.0: jnp.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar=0.0: jnp.minimum(x, scalar),
    "_hypot_scalar": lambda x, scalar=0.0: jnp.hypot(x, scalar),
    "_equal_scalar": lambda x, scalar=0.0: (x == scalar).astype(x.dtype),
    "_not_equal_scalar": lambda x, scalar=0.0: (x != scalar).astype(x.dtype),
    "_greater_scalar": lambda x, scalar=0.0: (x > scalar).astype(x.dtype),
    "_greater_equal_scalar":
        lambda x, scalar=0.0: (x >= scalar).astype(x.dtype),
    "_lesser_scalar": lambda x, scalar=0.0: (x < scalar).astype(x.dtype),
    "_lesser_equal_scalar":
        lambda x, scalar=0.0: (x <= scalar).astype(x.dtype),
    "_logical_and_scalar":
        lambda x, scalar=0.0: ((x != 0) & (scalar != 0)).astype(x.dtype),
    "_logical_or_scalar":
        lambda x, scalar=0.0: ((x != 0) | (scalar != 0)).astype(x.dtype),
    "_logical_xor_scalar":
        lambda x, scalar=0.0: ((x != 0) ^ (scalar != 0)).astype(x.dtype),
    "_scatter_plus_scalar": lambda x, scalar=0.0: x + scalar,
}

for _name, _f in _SCALAR.items():
    register_op(_name)(_f)


@register_op("smooth_l1")
def _smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2,
                     0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


@register_op("add_n", aliases=("ElementWiseSum", "_sum_nary"))
def _add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# -- symbolic metadata -------------------------------------------------------
from .registry import get_op as _get_op

def _leaky_inputs(params):
    if params.get("act_type", "leaky") == "prelu":
        return ("data", "gamma")
    return ("data",)

_get_op("LeakyReLU").active_inputs = _leaky_inputs


# scalar-arith ops take the scalar as a traced arg so varying Python
# scalars in a loop do not trigger one compilation per distinct value
for _name in _SCALAR:
    _get_op(_name).dynamic_params = ("scalar",)
_get_op("smooth_l1").dynamic_params = ("scalar",)
