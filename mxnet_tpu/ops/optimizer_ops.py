"""Fused optimizer update ops.

Reference: ``src/operator/optimizer_op.cc:43-651`` (sgd_update,
sgd_mom_update, mp_sgd*, adam_update, rmsprop, ftrl, signsgd, signum, ftml,
nag, adagrad).  Each op is one fused XLA computation; the eager dispatcher
marks the weight/state inputs as donated (``Op.donate``) so the update reuses
the parameter's HBM buffer — the TPU equivalent of the reference's in-place
kernel writes.

All ops return the updated tensors (weight first, then states); callers
rebind their NDArrays to the outputs.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _rescale_clip(grad, rescale_grad, clip_gradient, wd=None, weight=None):
    # wd/rescale_grad may be traced scalars (dynamic params) — no Python
    # branching on their values; clip_gradient stays a static param.
    grad = grad * rescale_grad
    if clip_gradient is not None and not hasattr(clip_gradient, "dtype") \
            and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    if wd is not None and weight is not None:
        grad = grad + wd * weight
    return grad


@register_op("sgd_update", donate=(0,))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register_op("sgd_mom_update", num_outputs=2, donate=(0, 2))
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom = momentum * mom - lr * g
    return weight + mom, mom


@register_op("nag_mom_update", num_outputs=2, donate=(0, 2))
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom = momentum * mom + g
    return weight - lr * (g + momentum * mom), mom


@register_op("mp_sgd_update", num_outputs=2, donate=(0, 2))
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    # multi-precision: bf16/fp16 weight with fp32 master copy
    # (reference mp_sgd_update, optimizer_op.cc:43+)
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient,
                      wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register_op("mp_sgd_mom_update", num_outputs=3, donate=(0, 2, 3))
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient,
                      wd, weight32)
    mom = momentum * mom - lr * g
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register_op("adam_update", num_outputs=3, donate=(0, 2, 3))
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * mean / (jnp.sqrt(var) + epsilon)
    return w, mean, var


@register_op("rmsprop_update", num_outputs=2, donate=(0, 2))
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n


@register_op("rmspropalex_update", num_outputs=4, donate=(0, 2, 3, 4))
def _rmspropalex_update(weight, grad, n, g_avg, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_avg = gamma1 * g_avg + (1 - gamma1) * g
    delta = gamma2 * delta - lr * g / jnp.sqrt(n - jnp.square(g_avg) +
                                               epsilon)
    w = weight + delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n, g_avg, delta


@register_op("ftrl_update", num_outputs=3, donate=(0, 2, 3))
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z) <= lamda1, jnp.zeros_like(weight),
        -(z - jnp.sign(z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, z, new_n


@register_op("ftml_update", num_outputs=4, donate=(0, 2, 3, 4))
def _ftml_update(weight, grad, d, v, z, lr=0.001, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_grad, wd, weight)
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * \
        (jnp.sqrt(v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1 - beta1) * g - sigma * weight
    w = -z / d_t
    return w, d_t, v, z


@register_op("signsgd_update", donate=(0,))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update", num_outputs=2, donate=(0, 2))
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mom = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom)
    return w, mom


@register_op("_sparse_adagrad_update", num_outputs=2, donate=(0, 2),
             aliases=("adagrad_update",))
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    history = history + jnp.square(g)
    return weight - lr * g / (jnp.sqrt(history) + epsilon), history


@register_op("adadelta_update", num_outputs=3, donate=(0, 2, 3))
def _adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                     wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g + epsilon) * g
    acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, acc_g, acc_delta


@register_op("adamax_update", num_outputs=3, donate=(0, 2, 3))
def _adamax_update(weight, grad, mean, var, lr=0.002, beta1=0.9, beta2=0.999,
                   epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    mean = beta1 * mean + (1 - beta1) * g
    var = jnp.maximum(beta2 * var, jnp.abs(g))
    w = weight - (lr / (1 - beta1 ** t)) * mean / (var + epsilon)
    return w, mean, var


@register_op("nadam_update", num_outputs=3, donate=(0, 2, 3))
def _nadam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, t=1, schedule_decay=0.004, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient, wd, weight)
    m_t = beta1 * (1 - 0.5 * 0.96 ** (t * schedule_decay))
    m_t1 = beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    g_hat = g / (1 - m_t)                      # prod approximation per step
    m_hat = mean / (1 - m_t1)
    m_bar = (1 - m_t) * g_hat + m_t1 * m_hat
    w = weight - lr * m_bar / (jnp.sqrt(var / (1 - beta2 ** t)) + epsilon)
    return w, mean, var


# -- dynamic scalar params (avoid per-step recompiles; see registry) --------
from .registry import get_op as _get_op

_DYN = ("lr", "wd", "rescale_grad", "momentum", "t", "wd_lh", "beta1",
        "beta2", "gamma1", "gamma2", "rho", "lamda1", "beta")
for _name in ("sgd_update", "sgd_mom_update", "nag_mom_update",
              "mp_sgd_update", "mp_sgd_mom_update", "adam_update",
              "rmsprop_update", "rmspropalex_update", "ftrl_update",
              "ftml_update", "signsgd_update", "signum_update",
              "_sparse_adagrad_update", "adadelta_update", "adamax_update",
              "nadam_update"):
    _get_op(_name).dynamic_params = _DYN
