"""Structured run-event log — ``events.jsonl``.

A durable, append-only record of the events that explain a failed or
slow run after the fact: compiles (with blame when derivable), guard
trips, chaos injections, preemptions, retries, dataloader respawns and
checkpoint commits.  One JSON object per line::

    {"ts": 1722700000.123, "ev": "guard", "pid": 4242, "seq": 17, ...}

**Off by default, zero per-event cost when off.**  The ``MXNET_OBS``
env knob mirrors ``MXNET_SAN``: unset/``0``/``off`` disables
everything (``emit`` is one cached-env check and returns); ``all``/
``1``/``on`` records every category; a comma list
(``MXNET_OBS=compile,guard,checkpoint``) records only those.  The
writer is created lazily on the first recorded event — with the knob
unset no file is ever opened.

Categories: ``compile``, ``guard``, ``chaos``, ``checkpoint``,
``preempt``, ``retry``, ``respawn``, ``warning``, ``kvstore``,
``serve`` (plus anything a caller passes — unknown categories are
recorded when ``all`` is on).

The ``serve`` category carries the serving control trail as ``kind``
fields: ``load`` / ``load_failed`` / ``unload`` / ``alias`` /
``unalias`` / ``compile`` (bucket blame) plus the fault-tolerance
kinds — ``shed`` (admission rejected), ``expired`` (deadline passed
before dispatch), ``cancelled`` (caller reclaimed its slot),
``dispatcher_restart`` / ``unhealthy`` (supervision), ``drain`` /
``cutover_flush`` (graceful teardown) and ``health`` (state-machine
transitions; see docs/serving.md).

The ``autotune`` category is the measured-cost tuner's trial trail
(``kind``: ``trial_start`` / ``trial_result`` / ``pruned`` /
``promoted`` / ``winner``, each carrying the candidate config and its
score — docs/autotuning.md).

Durability discipline (the same machinery family as
``resilience.checkpoint``): each line is ONE ``os.write`` on an
``O_APPEND`` fd — the kernel serializes appends, so concurrent
threads and even a second process on the same path never interleave
bytes mid-line — and the directory is fsynced once when the file is
created (``resilience.checkpoint.fsync_dir``).  A crash can lose at
most the final unflushed line, never tear an earlier one.

Rate cap: at most ``MXNET_OBS_RATE`` events per second (default 200;
0 = uncapped).  Excess events are counted, not queued, and the next
admitted event carries ``"dropped": N`` so the gap is visible in the
log itself.
"""

from __future__ import annotations

import json
import os
import time

from .. import sanitizer as _san
from . import metrics as _metrics

__all__ = ["enabled", "emit", "emitter", "watch_jit", "configure",
           "reopen", "path", "read_events", "tail_records"]

_CATEGORIES = ("compile", "guard", "chaos", "checkpoint", "preempt",
               "retry", "respawn", "warning", "kvstore", "membership",
               "supervisor", "watchdog", "serve", "decode", "fleet",
               "autotune", "quantize", "iraudit", "sched")


def _spec():
    raw = os.environ.get("MXNET_OBS", "").strip().lower()
    if not raw or raw in ("0", "off", "none", "false"):
        return None
    if raw in ("1", "on", "all", "true"):
        return "all"
    return frozenset(p.strip() for p in raw.split(",") if p.strip())


def enabled(category=None):
    """Is event recording on (for *category*, or at all)?  Read from
    the environment each call, like ``sanitizer.enabled`` — tests and
    the pytest harness monkeypatch ``MXNET_OBS`` freely."""
    spec = _spec()
    if spec is None:
        return False
    if spec == "all" or category is None:
        return True
    return category in spec


class _Writer:
    """Appending JSONL writer: O_APPEND single-write lines, creation
    fsync, token-bucket rate cap, monotonically increasing ``seq``."""

    def __init__(self, path, rate):
        self._path = path
        self._rate = rate
        self._fd = None
        self._lock = _san.lock(label="obs.events.writer")
        self._seq = 0
        self._dropped = 0
        self._window_start = 0.0
        self._window_count = 0

    def _open(self):
        # only reached from write() with self._lock held
        dirname = os.path.dirname(os.path.abspath(self._path))
        created = not os.path.exists(self._path)
        self._fd = os.open(  # graftlint: disable=JG010
            self._path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        if created:
            from ..resilience.checkpoint import fsync_dir
            fsync_dir(dirname)
        else:
            # resuming an existing log (a supervisor-restarted job, or
            # the parent writing between child incarnations): continue
            # from the last recorded seq so the combined file stays
            # monotone across the restart boundary — restart points are
            # still attributable via the per-line pid.  _open only runs
            # from write() with self._lock held (same as _fd above).
            self._seq = max(  # graftlint: disable=JG010
                self._seq, _last_seq(self._path))

    def reset_fd(self):
        """Close the fd and forget the cached seq: the next write
        re-opens and re-reads the tail (multi-process seq handoff)."""
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def write(self, category, fields):
        now = time.time()
        # the rate window runs on the monotonic clock: an NTP step
        # backward must not freeze a saturated window (only the ts
        # FIELD wants wall time)
        mono = time.monotonic()
        with self._lock:
            if self._rate > 0:
                if mono - self._window_start >= 1.0:
                    self._window_start = mono
                    self._window_count = 0
                if self._window_count >= self._rate:
                    self._dropped += 1
                    _metrics.counter(
                        "obs_events_dropped_total",
                        "events over the MXNET_OBS_RATE cap").inc()
                    return False
                self._window_count += 1
            if self._fd is None:
                self._open()
            self._seq += 1
            rec = {"ts": round(now, 6), "ev": category,
                   "pid": os.getpid(), "seq": self._seq}
            if self._dropped:
                rec["dropped"] = self._dropped
                self._dropped = 0
            rec.update(fields)
            line = json.dumps(rec, default=_json_fallback,
                              separators=(",", ":")) + "\n"
            os.write(self._fd, line.encode("utf-8"))
        _metrics.counter("obs_events_total",
                         "structured run events written").inc()
        return True

    def close(self):
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


def tail_records(path, max_bytes=1 << 16):
    """Parsed JSON records from the last *max_bytes* of an events
    file, oldest first.  The first line of a mid-file seek is usually
    torn — unparseable lines are skipped, an unreadable file yields
    [].  Shared by the writer's seq handoff and the supervisor's
    flight-record tail."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def _last_seq(path):
    """The last record's ``seq`` in an existing events file; 0 when
    unreadable or seq-less."""
    for rec in reversed(tail_records(path)):
        seq = rec.get("seq") if isinstance(rec, dict) else None
        if isinstance(seq, int):
            return seq
    return 0


def _json_fallback(obj):
    """Events must never fail to serialize — degrade to repr."""
    try:
        return repr(obj)[:200]
    except Exception:
        return "<unrepresentable>"


_writer = None
_writer_lock = _san.lock(label="obs.events.singleton")


def path():
    """The configured event-log path (the file may not exist yet)."""
    if _writer is not None:
        return _writer._path
    from ..config import get_env
    return get_env("MXNET_OBS_PATH")


def _get_writer():
    global _writer
    if _writer is None:
        with _writer_lock:
            if _writer is None:
                from ..config import get_env
                _writer = _Writer(path(),
                                  int(get_env("MXNET_OBS_RATE")))
    return _writer


def configure(path=None, rate=None):
    """Rebind the writer (tests; call before the first emit of the new
    run segment).  ``configure()`` with no args closes and resets so
    the next emit re-reads the environment."""
    global _writer
    with _writer_lock:
        if _writer is not None:
            _writer.close()
        _writer = None
        if path is not None:
            os.environ["MXNET_OBS_PATH"] = path
        if rate is not None:
            os.environ["MXNET_OBS_RATE"] = str(rate)


def reopen():
    """Force the writer to re-open (and re-read the tail seq) on its
    next emit.  The supervisor calls this between child incarnations:
    parent and children share one ``events.jsonl``, and a cached seq
    from before a child's lifetime would break the monotone-seq
    contract the file otherwise keeps."""
    if _writer is not None:
        _writer.reset_fd()


def emit(category, **fields):
    """Record one event if *category* is enabled.  Returns True when a
    line was written (False: disabled or rate-capped).  Never raises
    on IO problems — telemetry must not take down training — but does
    count failures."""
    if not enabled(category):
        return False
    try:
        return _get_writer().write(category, fields)
    except Exception:
        _metrics.counter("obs_events_errors_total",
                         "event-log write failures").inc()
        return False


def emitter(category):
    """Partial application of :func:`emit` for call sites that fire
    the same category repeatedly."""
    def _emit(**fields):
        return emit(category, **fields)
    return _emit


def read_events(p=None):
    """Parse an events.jsonl file back into dicts (tests, post-mortem
    tooling).  Raises on malformed lines — a torn log is a bug."""
    out = []
    with open(p or path(), encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# -- compile events with blame ----------------------------------------------

class _CompileWatch:
    """Host-side jit-cache watcher emitting ``compile`` events.

    The graftsan recompile sanitizer reports blamed cache misses when
    a developer opts in; this wrapper bridges the same signature-diff
    machinery into always-available telemetry: every compile (warmup
    included) is an event, and post-warmup misses carry the churned
    leaves.  Transparent proxy otherwise (``lower``/``_cache_size``
    stay reachable).

    Deliberately NOT unified with graftsan's JitWatch core: this
    module must work when ``tools/`` is absent (installed package),
    so graftsan is only a soft import for the blame diff — a shared
    watcher would make the dev-tooling tree load-bearing for
    production telemetry."""

    def __init__(self, fn, name):
        self._fn = fn
        self._name = name
        self._lock = _san.lock(label="obs.compilewatch.%s" % name)
        self._last_sig = None
        self._calls = 0

    def _signature(self, args, kwargs):
        try:
            from tools.graftsan.recompile import signature
            return signature(args, kwargs)
        except Exception:
            return None

    def _blame(self, prev, cur):
        if prev is None or cur is None:
            return []
        try:
            from tools.graftsan.recompile import diff_signatures
            return diff_signatures(prev, cur)
        except Exception:
            return []

    def __call__(self, *args, **kwargs):
        size_of = getattr(self._fn, "_cache_size", None)
        before = size_of() if size_of else None
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        after = size_of() if size_of else None
        missed = (before is not None and after is not None
                  and after > before)
        if missed:
            sig = self._signature(args, kwargs)
            with self._lock:
                calls = self._calls
                blame = self._blame(self._last_sig, sig) if calls \
                    else []
                self._last_sig = sig
                self._calls += 1
            emit("compile", fn=self._name, call=calls + 1,
                 cache_size=after, seconds=round(dt, 4),
                 warmup=calls == 0,
                 **({"blame": blame[:8]} if blame else {}))
        else:
            with self._lock:
                if after is not None:
                    sig = self._signature(args, kwargs)
                    self._last_sig = sig
                self._calls += 1
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def watch_jit(fn, name):
    """Wrap a jitted callable so its compiles become ``compile``
    events.  Identity when the ``compile`` category is off at wrap
    time (same created-while-off semantics as the sanitizer bridge)."""
    if not enabled("compile"):
        return fn
    if isinstance(fn, _CompileWatch):
        return fn
    return _CompileWatch(fn, name)
