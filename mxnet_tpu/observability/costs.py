"""Per-op HLO cost attribution — the MFU decompose engine.

``jit(...).lower(...).compile().cost_analysis()`` answers "how many
flops does the whole program do", which is enough for ONE MFU number
but not for an optimization queue: a 4.9%-MFU step needs to say
*which op* sits on the roofline's memory-bound floor.  XLA does not
expose per-instruction costs, so this module walks the lowered
StableHLO text with an analytic cost model (TVM/Glow-style: exact
flop formulas for the contraction ops, element-count estimates for
the rest, operand+result bytes for traffic) and classifies every op
group against the machine balance point::

    intensity = flops / bytes          (arithmetic intensity)
    balance   = peak_flops / peak_bytes_per_s
    class     = compute-bound if intensity >= balance else memory-bound

The estimated time share of a group is the roofline time
``max(flops/peak_flops, bytes/peak_bw)`` normalized over the program —
the number that makes an MFU regression attributable to a named op
(ROADMAP item 3; bench.py --decompose persists it into the BENCH
json schema).

Totals are cross-checked against ``compiled.cost_analysis()`` when
available: the analytic model counts the UNOPTIMIZED program (before
fusion folds ops away), so ``flops_vs_xla`` near 1.0 means the model
is trustworthy and >1 quantifies how much XLA fused away.
"""

from __future__ import annotations

import re

__all__ = ["parse_hlo_ops", "cost_table", "format_table"]

# dtype byte widths for tensor<...x DTYPE> suffixes
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e5m2": 1, "f8e4m3fn": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i1": 1, "pred": 1,
    "c64": 8, "c128": 16,
}

_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_OP_RE = re.compile(r'=\s+"?(?:stablehlo|mhlo|chlo)\.([a-zA-Z0-9_]+)"?')
_CONTRACT_RE = re.compile(r"contracting_dims\s*=\s*\[([0-9,\s]*)\]")
_BATCH_RE = re.compile(r"batching_dims\s*=\s*\[([0-9,\s]*)\]")
_FEATURE_GROUP_RE = re.compile(r"feature_group_count\s*=\s*(\d+)")
_KERNEL_SPEC_RE = re.compile(r"x\[([^\]]*)\]->")

# ops that are pure data movement / bookkeeping: zero flops, and for
# the shape-only ones zero meaningful traffic either.  Control-flow
# headers (while/if/case) are free too: their cost is their REGION
# bodies, which parse_hlo_ops charges with the loop multiplier.
_FREE_OPS = frozenset([
    "constant", "iota", "reshape", "bitcast_convert", "transpose",
    "broadcast_in_dim", "broadcast", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "reverse",
    "get_tuple_element", "tuple", "optimization_barrier", "copy",
    "convert", "custom_call", "after_all", "create_token",
    "while", "if", "case", "return",
])

# one-flop-per-element ops get 1; costlier elementwise ops get a
# weight approximating their scalar op count (transcendentals)
_ELEMENTWISE_WEIGHT = {
    "tanh": 8, "exponential": 8, "log": 8, "logistic": 8, "power": 8,
    "sine": 8, "cosine": 8, "rsqrt": 4, "sqrt": 4, "divide": 4,
    "erf": 8, "atan2": 10, "expm1": 8, "log_plus_one": 8,
    "cbrt": 8, "tan": 10,
}


def _parse_tensor(spec):
    """'16x32xf32' / 'f32' -> (shape tuple, dtype, bytes)."""
    parts = spec.strip().split("x")
    if len(parts) == 1:
        dtype = parts[0]
        shape = ()
    else:
        dtype = parts[-1]
        try:
            shape = tuple(int(p) for p in parts[:-1])
        except ValueError:
            # dynamic dim ('?') or complex spec — treat unknown as 1
            shape = tuple(int(p) if p.isdigit() else 1
                          for p in parts[:-1])
    n = 1
    for s in shape:
        n *= s
    return shape, dtype, n * _DTYPE_BYTES.get(dtype, 4)


def _prod(seq):
    n = 1
    for s in seq:
        n *= s
    return n


def _int_list(raw):
    return [int(p) for p in raw.replace(" ", "").split(",") if p]


def _op_flops(op, line, operands, result):
    """Analytic flop count for one instruction.

    *operands*/*result* are (shape, dtype, bytes) triples; the result
    triple is the first result for multi-result ops."""
    rshape = result[0]
    rcount = _prod(rshape)
    if op == "dot_general" or op == "dot":
        # 2 * prod(result) * K, K = product of the lhs contracting dims
        m = _CONTRACT_RE.search(line)
        lhs_shape = operands[0][0] if operands else ()
        if m:
            dims = _int_list(m.group(1))
            k = _prod(lhs_shape[d] for d in dims
                      if d < len(lhs_shape))
        elif len(lhs_shape) >= 1:
            k = lhs_shape[-1]        # plain dot default
        else:
            k = 1
        return 2.0 * rcount * k
    if op == "convolution":
        # 2 * prod(out) * (kernel spatial) * in_channels / groups
        if len(operands) < 2:
            return 2.0 * rcount
        kshape = operands[1][0]
        spec = _KERNEL_SPEC_RE.search(line)
        if spec:
            labels = [p.strip() for p in spec.group(1).split(",")]
            spatial = _prod(kshape[i] for i, l in enumerate(labels)
                            if l not in ("i", "o") and i < len(kshape))
            try:
                in_ch = kshape[labels.index("i")]
            except (ValueError, IndexError):
                in_ch = 1
        else:
            # HWIO fallback: all but the last two dims are spatial
            spatial = _prod(kshape[:-2]) if len(kshape) >= 2 else 1
            in_ch = kshape[-2] if len(kshape) >= 2 else 1
        groups = 1
        g = _FEATURE_GROUP_RE.search(line)
        if g:
            groups = max(1, int(g.group(1)))
        return 2.0 * rcount * spatial * in_ch / groups
    if op in ("reduce", "reduce_window", "select_and_scatter"):
        # one combine per input element
        return float(_prod(operands[0][0])) if operands else float(rcount)
    if op in ("rng", "rng_bit_generator"):
        return 8.0 * rcount
    if op in ("sort",):
        n = _prod(operands[0][0]) if operands else rcount
        return 4.0 * n                  # ~n log n, flattened estimate
    if op in ("gather", "scatter", "select", "clamp", "compare",
              "maximum", "minimum", "and", "or", "xor", "not"):
        return float(rcount)
    return float(rcount) * _ELEMENTWISE_WEIGHT.get(op, 1)


_FUNC_RE = re.compile(r"func\.func\s+(?:(public|private)\s+)?@([\w$.\-]+)")
_CALL_RE = re.compile(r"(?:func\.)?call\s+@([\w$.\-]+)")
_INT_CONST_RE = re.compile(
    r"(%[\w#]+)\s*=\s*stablehlo\.constant\s+dense<(-?\d+)>\s*:"
    r"\s*tensor<(?:i32|i64|ui32|ui64)>")
_ITER_INIT_RE = re.compile(r"(%[\w#]+)\s*=\s*(%[\w#]+)")
_WHILE_CMP_RE = re.compile(
    r"stablehlo\.compare\s+(LT|LE),\s*(%[\w#]+),\s*(%[\w#]+)")


def _cost_row(line, op_match):
    """One {op, flops, bytes, shapes} row for an instruction line, or
    None when the line carries no tensor types."""
    op = op_match.group(1)
    tensors = [_parse_tensor(t) for t in _TENSOR_RE.findall(line)]
    if not tensors:
        return None
    # pretty form: "... : (operand types) -> result" or
    # "... : type" (every operand AND the result share the one
    # printed type — so count the %-operand refs, or a binary
    # add would be charged 2x tensor bytes instead of 3x and its
    # arithmetic intensity inflated 1.5x)
    if "->" in line.split(" : ")[-1] and len(tensors) >= 2:
        operands, results = tensors[:-1], tensors[-1:]
    else:
        seg = line[op_match.end():line.rfind(" : ")]
        n_operands = max(1, seg.count("%"))
        operands = [tensors[-1]] * n_operands
        results = tensors[-1:]
    flops = _op_flops(op, line, operands, results[0])
    byts = sum(t[2] for t in operands) + sum(t[2] for t in results)
    return {
        "op": op,
        "flops": flops,
        "bytes": float(byts),
        "shapes": "%s->%s" % (
            ",".join("x".join(map(str, t[0])) or "scalar"
                     for t in operands[:2]),
            "x".join(map(str, results[0][0])) or "scalar"),
    }


def _parse_functions(text):
    """Split StableHLO text into per-function op lists with LOOP
    multipliers resolved.

    Returns ``{fname: {"public": bool, "rows": [(row, mult)],
    "calls": [(callee, mult)]}}``.  *mult* is the product of the trip
    counts of the enclosing ``stablehlo.while`` regions: jax lowers
    ``lax.scan``/``fori_loop`` to a while whose cond compares the
    induction iterArg LT/LE a constant bound, with the body outlined
    into a ``func.func private`` reached via ``func.call`` — so a
    scanned matmul must charge trip_count x body, not 1x.  A while
    whose trip count is not statically visible multiplies by 1
    (conservative)."""
    funcs = {}
    cur = None            # current function record
    consts = {}           # %name -> int (scalar int constants, SSA)
    # scope stack: [depth_at_open, multiplier] for each open while
    # region; current multiplier = product over the stack
    scopes = []
    depth = 0
    pending_while = None  # iterArg -> init operand, for the next cond
    cond_scope = None     # scope collecting the cond of pending_while

    for line in text.splitlines():
        stripped = line.strip()
        fm = _FUNC_RE.search(line)
        if fm:
            cur = {"public": fm.group(1) != "private",
                   "rows": [], "calls": []}
            funcs[fm.group(2)] = cur
            consts = {}
            scopes = []
            depth = line.count("{") - line.count("}")
            pending_while = None
            cond_scope = None
            continue
        if cur is None:
            # bare op text with no func.func wrapper (tests, snippets):
            # treat everything before the first signature as an
            # implicit entry function
            if not _OP_RE.search(line):
                continue
            cur = {"public": True, "rows": [], "calls": []}
            funcs["<toplevel>"] = cur

        cm = _INT_CONST_RE.search(line)
        if cm:
            consts[cm.group(1)] = int(cm.group(2))

        if "stablehlo.while" in line and "=" in line:
            inside = line[line.find("(") + 1:line.rfind(")")] \
                if "(" in line else ""
            pending_while = dict(_ITER_INIT_RE.findall(inside))

        mult = 1
        for s in scopes:
            mult *= s[1]

        if pending_while is not None and stripped.startswith("cond"):
            # the cond region: runs trip+1 times, but holds only the
            # bound compare — charge it with the body multiplier once
            # the trip count is known (scope mult patched at "} do {")
            cond_scope = [depth + 1, 1, pending_while]
            scopes.append(cond_scope)
            depth += line.count("{") - line.count("}")
            continue
        if cond_scope is not None and stripped.startswith("}") \
                and "do" in stripped and "{" in stripped:
            # "} do {": close the cond scope, open the body scope with
            # the trip count inferred from the cond's compare
            trip = cond_scope[1] if cond_scope[1] > 1 else 1
            scopes.pop()
            scopes.append([depth, trip])
            pending_while = None
            cond_scope = None
            depth += line.count("{") - line.count("}")
            continue

        if cond_scope is not None:
            wm = _WHILE_CMP_RE.search(line)
            if wm:
                direction, it, bound = wm.groups()
                limit = consts.get(bound)
                init = consts.get(cond_scope[2].get(it, ""), 0)
                if limit is not None:
                    trip = limit - init + (1 if direction == "LE" else 0)
                    if trip > 0:
                        cond_scope[1] = trip

        om = _OP_RE.search(line)
        if om and om.group(1) not in _FREE_OPS:
            row = _cost_row(line, om)
            if row is not None:
                cur["rows"].append((row, mult))
        else:
            km = _CALL_RE.search(line)
            if km:
                cur["calls"].append((km.group(1), mult))

        depth += line.count("{") - line.count("}")
        while scopes and depth < scopes[-1][0]:
            scopes.pop()
            if scopes is not None and cond_scope is not None and \
                    (not scopes or cond_scope not in scopes):
                cond_scope = None
                pending_while = None
    return funcs


def parse_hlo_ops(text):
    """Walk lowered StableHLO/MHLO text; one cost row per
    instruction: ``{op, flops, bytes, shapes, count}``.  Lines that
    are not instructions (signatures, regions, returns) are skipped.

    Nested regions are priced honestly: ops inside a
    ``stablehlo.while`` body (and in functions the body calls — jax
    outlines scan/fori bodies into ``func.func private``) are
    multiplied by the statically-inferred trip count, so a scanned
    matmul costs trip_count x body flops, not 1x."""
    funcs = _parse_functions(text)
    if not funcs:
        return []

    # function multiplier: how many times each function runs per
    # program execution.  Public functions are entry points (1x);
    # private ones run once per call site times the caller's own
    # multiplier.  MLIR functions cannot recurse, so plain memoized
    # recursion over the caller edges terminates.
    callers = {}
    for fname, rec in funcs.items():
        for callee, mult in rec["calls"]:
            callers.setdefault(callee, []).append((fname, mult))

    memo = {}

    def fmult(fname):
        if fname in memo:
            return memo[fname]
        rec = funcs.get(fname)
        if rec is None:
            return 0
        if rec["public"]:
            memo[fname] = 1
            return 1
        edges = callers.get(fname)
        if not edges:
            # unreferenced private function: price it once rather
            # than silently dropping it (unusual dialect output)
            memo[fname] = 1
            return 1
        memo[fname] = 0            # break accidental cycles at 0
        total = sum(fmult(c) * m for c, m in edges)
        memo[fname] = total if total > 0 else 1
        return memo[fname]

    rows = []
    for fname, rec in funcs.items():
        fm = fmult(fname)
        if fm <= 0:
            continue
        for row, mult in rec["rows"]:
            n = fm * mult
            if n == 1:
                rows.append(dict(row, count=1))
            else:
                rows.append({
                    "op": row["op"],
                    "flops": row["flops"] * n,
                    "bytes": row["bytes"] * n,
                    "shapes": row["shapes"],
                    "count": n,
                })
    return rows


def cost_table(lowered=None, text=None, compiled=None, peak_flops=None,
               peak_bytes_s=None, top=None):
    """Build the per-op cost table for a lowered program.

    Pass a ``jax.stages.Lowered`` (``jit(f).lower(...)``), or raw
    StableHLO *text*.  With *peak_flops* and *peak_bytes_s* (probed or
    datasheet), each op group gets a roofline class and an estimated
    share of step time; without them only flops/bytes shares are
    filled.  Groups are keyed by (op kind, shape signature) so "the
    7x7 stem conv" and "the 1x1 bottleneck convs" stay separate rows.
    """
    if text is None:
        if lowered is None:
            raise ValueError("need a lowered program or HLO text")
        text = lowered.as_text()
        if compiled is None:
            try:
                compiled = lowered.compile()
            except Exception:
                compiled = None
    rows = parse_hlo_ops(text)

    groups = {}
    for r in rows:
        key = (r["op"], r["shapes"])
        g = groups.setdefault(key, {"op": r["op"], "shapes": r["shapes"],
                                    "count": 0, "flops": 0.0,
                                    "bytes": 0.0})
        g["count"] += 1
        g["flops"] += r["flops"]
        g["bytes"] += r["bytes"]

    total_flops = sum(g["flops"] for g in groups.values()) or 1.0
    total_bytes = sum(g["bytes"] for g in groups.values()) or 1.0
    balance = (peak_flops / peak_bytes_s
               if peak_flops and peak_bytes_s else None)

    out_rows = []
    total_time = 0.0
    for g in groups.values():
        intensity = g["flops"] / g["bytes"] if g["bytes"] else 0.0
        row = dict(g)
        row["intensity"] = round(intensity, 3)
        row["pct_flops"] = round(100.0 * g["flops"] / total_flops, 2)
        if balance is not None:
            row["class"] = ("compute-bound" if intensity >= balance
                            else "memory-bound")
            row["roofline_s"] = max(g["flops"] / peak_flops,
                                    g["bytes"] / peak_bytes_s)
            total_time += row["roofline_s"]
        out_rows.append(row)
    if total_time > 0:
        for row in out_rows:
            row["pct_time"] = round(100.0 * row.pop("roofline_s")
                                    / total_time, 2)
        out_rows.sort(key=lambda r: -r["pct_time"])
    else:
        out_rows.sort(key=lambda r: -r["pct_flops"])
    if top:
        dropped = out_rows[top:]
        if dropped:
            rest = {"op": "(other %d groups)" % len(dropped),
                    "shapes": "", "count": sum(d["count"] for d in dropped),
                    "flops": sum(d["flops"] for d in dropped),
                    "bytes": sum(d["bytes"] for d in dropped),
                    "intensity": 0.0,
                    "pct_flops": round(sum(d["pct_flops"]
                                           for d in dropped), 2)}
            if "pct_time" in (dropped[0] if dropped else {}):
                rest["pct_time"] = round(sum(d["pct_time"]
                                             for d in dropped), 2)
                rest["class"] = "-"
            out_rows = out_rows[:top] + [rest]

    table = {
        "rows": out_rows,
        "total_flops": total_flops,
        "total_bytes": total_bytes,
        "machine_balance": round(balance, 3) if balance else None,
        "peak_flops": peak_flops,
        "peak_bytes_s": peak_bytes_s,
    }
    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            if ca:
                table["xla_cost_analysis"] = {
                    k: float(v) for k, v in ca.items()
                    if isinstance(v, (int, float)) and "{" not in k}
                xf = table["xla_cost_analysis"].get("flops")
                if xf:
                    table["flops_vs_xla"] = round(total_flops / xf, 3)
        except Exception:
            pass
    return table


def format_table(table, limit=20):
    """Human-readable text rendering of :func:`cost_table`."""
    have_time = any("pct_time" in r for r in table["rows"])
    hdr = "%-18s %-34s %5s %12s %12s %9s %6s" % (
        "op", "shapes", "n", "gflops", "MB", "int.", "%fl")
    if have_time:
        hdr += " %6s %-14s" % ("%time", "roofline")
    lines = [hdr, "-" * len(hdr)]
    for r in table["rows"][:limit]:
        line = "%-18s %-34s %5d %12.3f %12.2f %9.1f %6.2f" % (
            r["op"], r["shapes"][:34], r["count"], r["flops"] / 1e9,
            r["bytes"] / 1e6, r.get("intensity", 0.0), r["pct_flops"])
        if have_time:
            line += " %6.2f %-14s" % (r.get("pct_time", 0.0),
                                      r.get("class", "-"))
        lines.append(line)
    lines.append("total: %.3f gflops, %.2f MB analytic%s" % (
        table["total_flops"] / 1e9, table["total_bytes"] / 1e6,
        ", %.2fx of XLA's %.3f gflops" % (
            table["flops_vs_xla"],
            table["xla_cost_analysis"]["flops"] / 1e9)
        if table.get("flops_vs_xla") else ""))
    return "\n".join(lines)
