"""Metrics registry — thread-safe counters, gauges and histograms.

One process-wide :class:`MetricsRegistry` (module-level ``REGISTRY``)
that every subsystem records into and that two consumers read:

* ``snapshot()`` — a JSON-able dict (the CI smoke stage and tests);
* ``exposition()`` — Prometheus text format (what a fleet scraper
  pulls; names are prefixed ``mxnet_`` and sanitized).

Instruments are **always on**: creation and update take per-instrument
locks built from the :mod:`..sanitizer` factories, so a ``pytest
--graftsan`` run audits the registry's own locking discipline like any
other subsystem.  Hot paths keep a module-level reference to their
instrument (one uncontended lock per update, no registry lookup); the
registry lookup itself is lock-free on the hit path (CPython dict
reads are atomic) and only locks to create.

The profiler's ``bump_counter``/``counter_value``/``counters``/
``reset_counters`` surface is a compatibility layer over this
registry (see profiler.py) — the dispatch/compile counters the fused
-step tests assert are the same instruments a scraper sees.
"""

from __future__ import annotations

import bisect

from .. import sanitizer as _san

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "counter", "gauge", "histogram", "snapshot",
           "exposition", "reset"]

# latency-style default buckets (seconds): sub-ms dispatch overheads
# through minute-scale checkpoint writes
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Counter:
    """Monotonically increasing count."""

    kind = "counter"
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = _san.lock(label="metrics.%s" % name)

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counter %s cannot decrease (inc %r)"
                             % (self.name, n))
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def _snap(self):
        return {"kind": "counter", "value": self.value}


class Gauge:
    """Point-in-time value (queue depth, in-flight batches, ...)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = _san.lock(label="metrics.%s" % name)

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def _reset(self):
        with self._lock:
            self._value = 0

    def _snap(self):
        return {"kind": "gauge", "value": self.value}


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: ``le``
    upper bounds plus ``+Inf``, with running count and sum)."""

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram %s needs at least one bucket"
                             % name)
        self._counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = _san.lock(label="metrics.%s" % name)

    def observe(self, v):
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def time(self):
        """Context manager observing the elapsed wall seconds."""
        return _Timer(self)

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def _reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def _snap(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, out = 0, {}
        for b, c in zip(self.buckets, counts):
            cum += c
            out["%g" % b] = cum
        out["+Inf"] = total
        return {"kind": "histogram", "count": total, "sum": s,
                "buckets": out}


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        import time
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time
        self._hist.observe(time.perf_counter() - self._t0)


class MetricsRegistry:
    """Get-or-create instrument store.

    The hit path reads the instrument dict WITHOUT the registry lock
    (atomic under the GIL and under free-threading's per-dict locking);
    only creation locks.  Re-requesting a name with a different
    instrument kind is an error — two subsystems silently sharing a
    name would corrupt both series.
    """

    def __init__(self):
        self._instruments = {}
        self._lock = _san.lock(label="metrics.registry")

    def _get_or_create(self, cls, name, help, **kwargs):
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, help=help, **kwargs)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                "instrument %r already registered as %s, requested %s"
                % (name, inst.kind, cls.kind))
        return inst

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help,
                                   buckets=buckets)

    def get(self, name):
        return self._instruments.get(name)

    def names(self):
        return sorted(self._instruments)

    def snapshot(self, kind=None):
        """{name: instrument snapshot} — a consistent-per-instrument
        JSON-able view (cross-instrument consistency is not promised;
        each instrument locks individually)."""
        out = {}
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            if kind is None or inst.kind == kind:
                out[name] = inst._snap()
        return out

    def reset(self):
        """Zero every instrument (instruments stay registered)."""
        for inst in list(self._instruments.values()):
            inst._reset()

    def reset_counters(self):
        for inst in list(self._instruments.values()):
            if inst.kind == "counter":
                inst._reset()

    # -- Prometheus text exposition -----------------------------------
    @staticmethod
    def _prom_name(name):
        safe = "".join(c if (c.isalnum() or c == "_") else "_"
                       for c in name)
        if not safe or not (safe[0].isalpha() or safe[0] == "_"):
            safe = "_" + safe
        return "mxnet_" + safe

    @staticmethod
    def _prom_val(v):
        if isinstance(v, float):
            return repr(v)
        return str(v)

    def exposition(self):
        """Prometheus text format, instruments sorted by name."""
        lines = []
        for name in sorted(self._instruments):
            inst = self._instruments[name]
            pn = self._prom_name(name)
            if inst.help:
                lines.append("# HELP %s %s"
                             % (pn, inst.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (pn, inst.kind))
            if inst.kind == "histogram":
                snap = inst._snap()
                for le, c in snap["buckets"].items():
                    lines.append('%s_bucket{le="%s"} %d' % (pn, le, c))
                lines.append("%s_sum %s"
                             % (pn, self._prom_val(snap["sum"])))
                lines.append("%s_count %d" % (pn, snap["count"]))
            else:
                lines.append("%s %s"
                             % (pn, self._prom_val(inst.value)))
        return "\n".join(lines) + "\n"


#: the process-wide registry every subsystem records into
REGISTRY = MetricsRegistry()


def counter(name, help=""):
    return REGISTRY.counter(name, help)


def gauge(name, help=""):
    return REGISTRY.gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help, buckets)


def snapshot(kind=None):
    return REGISTRY.snapshot(kind)


def exposition():
    return REGISTRY.exposition()


def reset():
    REGISTRY.reset()
