"""Unified telemetry for the TPU-native framework.

Three parts (docs/observability.md):

* :mod:`.metrics` — an always-on, thread-safe instrument registry
  (counters / gauges / histograms) with JSON snapshots and
  Prometheus-style text exposition.  The profiler's historical
  ``bump_counter``/``counters`` dispatch-and-compile counter surface
  is a compatibility layer over this registry, so every number a test
  asserted before this subsystem existed still comes from the same
  place a fleet scraper reads.

* :mod:`.events` — an opt-in structured run-event log
  (``events.jsonl``; ``MXNET_OBS`` env knob, off by default with zero
  per-event cost) recording compiles with blame, non-finite-guard
  trips, chaos injections, preemptions, retries, worker respawns and
  checkpoint commits, so a failed run is diagnosable post-mortem from
  one file.

* :mod:`.costs` — per-op HLO cost attribution: an analytic
  flops/bytes model over a lowered program plus roofline
  classification against probed peaks, turning a single MFU number
  into a per-op optimization queue (``bench.py --decompose``,
  ``tools/mfu_sweep.py --decompose``).

Import discipline: this package depends only on the stdlib,
``..sanitizer`` (lock factories, so graftsan can audit instrument
locking) and ``..config`` — it must stay importable from every
subsystem (ndarray, io, kvstore, resilience) without cycles.
"""

from __future__ import annotations

from . import metrics
from . import events
from . import costs

__all__ = ["metrics", "events", "costs"]
