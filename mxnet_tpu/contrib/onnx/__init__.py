"""ONNX interop (reference: python/mxnet/contrib/onnx — mx2onnx
exporter + onnx2mx importer).

Stance: the ``onnx`` package is not available in this environment
(zero-egress image), so the converters are gated, exactly like the
reference gates on ``import onnx``.  When onnx IS installed, a
StableHLO-era build has a better path than the reference's op-by-op
converter: hybridize the model to one XLA program and use
jax.export/ONNX tooling.  ``export_model``/``import_model`` keep the
reference entry-point names and raise with that guidance until onnx is
present."""

from __future__ import annotations

__all__ = ["export_model", "import_model"]

_MSG = ("onnx is not installed in this environment. The reference "
        "(python/mxnet/contrib/onnx) gates on `import onnx` the same "
        "way. With onnx available, export hybridized models through "
        "jax.export (one XLA program) rather than per-op conversion.")


def export_model(*args, **kwargs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ImportError(_MSG)
    raise NotImplementedError(
        "onnx export for this build is tracked but not yet implemented; "
        "use the checkpoint format (prefix-symbol.json + params) for "
        "interop with reference tooling")


def import_model(*args, **kwargs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise ImportError(_MSG)
    raise NotImplementedError(
        "onnx import for this build is tracked but not yet implemented")
