"""ONNX interop (reference: python/mxnet/contrib/onnx — mx2onnx
exporter + onnx2mx importer).

The ``onnx`` wheel does not exist in this image, but an .onnx file is
just a serialized protobuf: ``_proto.py`` implements the required
``ModelProto`` subset directly on the wire format, ``mx2onnx.py``
converts Symbol graphs + params to it, and ``onnx2mx.py`` parses ONNX
files back into ``(sym, arg_params, aux_params)``.  Entry-point names
match the reference (``export_model``; ``import_model``), so reference
user code ports unchanged.
"""

from __future__ import annotations

from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model  # noqa: F401

__all__ = ["export_model", "import_model"]
