"""ONNX -> Symbol importer.

Mirrors ``python/mxnet/contrib/onnx/onnx2mx/import_model.py`` (entry
point) + ``_op_translations.py``, decoding the protobuf with the
in-repo codec.  Returns ``(sym, arg_params, aux_params)`` exactly like
the reference, ready for ``mx.mod.Module`` binding.
"""

from __future__ import annotations

import numpy as _np

from . import _proto as P


def _attr(attrs, key, default=None):
    return attrs.get(key, default)


class _Builder:
    def __init__(self, initializers):
        self.inits = initializers
        self.values = {}       # onnx value name -> Symbol
        self.aux_names = set()
        self.consumed = set()  # initializer names folded into attrs

    def sym_for(self, name, sym_mod):
        if name not in self.values:
            # free value: either an initializer-backed weight or an input
            self.values[name] = sym_mod.Variable(name)
        return self.values[name]


def _conv(b, sym, node, ins):
    a = node["attrs"]
    kwargs = {"kernel": tuple(a.get("kernel_shape", ())),
              "num_group": int(a.get("group", 1)),
              "no_bias": len(ins) < 3}
    if "strides" in a:
        kwargs["stride"] = tuple(a["strides"])
    if "pads" in a:
        p = a["pads"]
        kwargs["pad"] = tuple(p[:len(p) // 2])
    if "dilations" in a:
        kwargs["dilate"] = tuple(a["dilations"])
    w = b.inits.get(node["inputs"][1])
    if w is None:
        raise NotImplementedError(
            "Conv node %r: weight %r is a runtime graph input, not an "
            "initializer — num_filter cannot be inferred"
            % (node["name"], node["inputs"][1]))
    kwargs["num_filter"] = int(w.shape[0])
    return sym.Convolution(*ins, name=node["name"] or None, **kwargs)


def _bn(b, sym, node, ins):
    a = node["attrs"]
    for nm in node["inputs"][3:5]:
        b.aux_names.add(nm)
    return sym.BatchNorm(*ins, eps=float(a.get("epsilon", 1e-5)),
                         momentum=float(a.get("momentum", 0.9)),
                         fix_gamma=False, name=node["name"] or None)


def _pool(op_type):
    def f(b, sym, node, ins):
        a = node["attrs"]
        kwargs = {"pool_type": "max" if "Max" in op_type else "avg"}
        if op_type.startswith("Global"):
            kwargs.update(global_pool=True, kernel=(1, 1))
        else:
            kwargs["kernel"] = tuple(a["kernel_shape"])
            if "strides" in a:
                kwargs["stride"] = tuple(a["strides"])
            if "pads" in a:
                p = a["pads"]
                half = len(p) // 2
                begin, end = tuple(p[:half]), tuple(p[half:])
                kwargs["pad"] = begin
                if end != begin:
                    # asymmetric END padding is how mx2onnx encodes
                    # pooling_convention='full' for MAX pooling at
                    # opset 9 (no ceil_mode); the extra end pad is
                    # always < stride there.  Anything else (average
                    # pooling, or end pads from another producer's
                    # SAME-padding scheme) has no MXNet Pooling
                    # equivalent — refuse rather than silently change
                    # values.
                    stride = kwargs.get("stride",
                                        (1,) * len(begin))
                    if "Max" not in op_type or any(
                            e - b < 0 or e - b >= s for e, b, s in
                            zip(end, begin, stride)):
                        raise NotImplementedError(
                            "asymmetric pooling padding %r is not "
                            "representable (only this package's "
                            "'full'-convention encoding imports)"
                            % (p,))
                    kwargs["pooling_convention"] = "full"
        return sym.Pooling(*ins, name=node["name"] or None, **kwargs)
    return f


def _gemm(b, sym, node, ins):
    a = node["attrs"]
    if int(a.get("transA", 0)):
        raise NotImplementedError("Gemm with transA")
    w_name = node["inputs"][1]
    w = b.inits.get(w_name)
    if w is None:
        raise NotImplementedError(
            "Gemm node %r: weight %r is a runtime graph input, not an "
            "initializer — num_hidden cannot be inferred"
            % (node["name"], node["inputs"][1]))
    data, weight = ins[0], ins[1]
    if not int(a.get("transB", 1)):
        # FullyConnected expects (out, in): fold the transpose into the
        # initializer
        b.inits[w_name] = _np.ascontiguousarray(w.T)
        w = b.inits[w_name]
    # fold alpha into the weight and beta into the bias so the
    # FullyConnected numerics match Gemm's alpha*A@B' + beta*C
    alpha = float(a.get("alpha", 1.0))
    if alpha != 1.0:
        b.inits[w_name] = b.inits[w_name] * _np.asarray(
            alpha, b.inits[w_name].dtype)
        w = b.inits[w_name]
    beta = float(a.get("beta", 1.0))
    has_bias = len(ins) >= 3
    if has_bias and beta == 0.0:
        ins = ins[:2]
        has_bias = False
    elif has_bias and beta != 1.0:
        c_name = node["inputs"][2]
        c = b.inits.get(c_name)
        if c is None:
            raise NotImplementedError(
                "Gemm node %r: beta=%g with a runtime bias input"
                % (node["name"], beta))
        b.inits[c_name] = c * _np.asarray(beta, c.dtype)
    fc_ins = [data, weight] + list(ins[2:])
    return sym.FullyConnected(*fc_ins, num_hidden=int(w.shape[0]),
                              no_bias=not has_bias, flatten=False,
                              name=node["name"] or None)


def _act(mx_act):
    def f(b, sym, node, ins):
        return sym.Activation(ins[0], act_type=mx_act,
                              name=node["name"] or None)
    return f


def _binary(mx_name):
    def f(b, sym, node, ins):
        return getattr(sym, mx_name)(*ins, name=node["name"] or None)
    return f


def _reshape(b, sym, node, ins):
    shape_name = node["inputs"][1]
    shape = b.inits[shape_name]
    b.consumed.add(shape_name)
    return sym.Reshape(ins[0], shape=tuple(int(s) for s in shape),
                       name=node["name"] or None)


def _dropout(b, sym, node, ins):
    return sym.Dropout(ins[0],
                       p=float(node["attrs"].get("ratio", 0.5)),
                       name=node["name"] or None)


def _softmax(b, sym, node, ins):
    return sym.softmax(ins[0],
                       axis=int(node["attrs"].get("axis", -1)),
                       name=node["name"] or None)


def _flatten(b, sym, node, ins):
    return sym.Flatten(ins[0], name=node["name"] or None)


def _lrn(b, sym, node, ins):
    a = node["attrs"]
    return sym.LRN(ins[0], alpha=float(a.get("alpha", 1e-4)),
                   beta=float(a.get("beta", 0.75)),
                   knorm=float(a.get("bias", 2.0)),
                   nsize=int(a["size"]), name=node["name"] or None)


def _pad(b, sym, node, ins):
    a = node["attrs"]
    p = a["pads"]
    half = len(p) // 2
    pw = []
    for i in range(half):
        pw += [p[i], p[half + i]]
    return sym.Pad(ins[0], mode=a.get("mode", "constant"),
                   pad_width=tuple(pw),
                   constant_value=float(a.get("value", 0.0)),
                   name=node["name"] or None)


def _transpose(b, sym, node, ins):
    return sym.transpose(ins[0],
                         axes=tuple(node["attrs"].get("perm", ())),
                         name=node["name"] or None)


def _clip(b, sym, node, ins):
    a = node["attrs"]
    return sym.clip(ins[0], a_min=float(a["min"]),
                    a_max=float(a["max"]), name=node["name"] or None)


def _leaky(b, sym, node, ins):
    return sym.LeakyReLU(ins[0],
                         slope=float(node["attrs"].get("alpha", 0.01)),
                         act_type="leaky", name=node["name"] or None)


def _reduce_mean(b, sym, node, ins):
    a = node["attrs"]
    return sym.mean(ins[0], axis=tuple(a.get("axes", ())) or None,
                    keepdims=bool(a.get("keepdims", 1)),
                    name=node["name"] or None)


def _slice(b, sym, node, ins):
    a = node["attrs"]
    # axes is optional in opset-9 Slice: default = leading axes in order
    axes = a.get("axes") or list(range(len(a.get("starts", []))))
    out = ins[0]
    for k, ax in enumerate(axes):
        end = a["ends"][k]
        out = sym.slice_axis(out, axis=int(ax),
                             begin=int(a["starts"][k]),
                             end=None if end >= 2 ** 31 - 1 else int(end))
    return out


def _identity(b, sym, node, ins):
    return sym.identity(ins[0], name=node["name"] or None)


def _cast(b, sym, node, ins):
    to = int(node["attrs"]["to"])
    return sym.cast(ins[0], dtype=P.DT_TO_NP[to],
                    name=node["name"] or None)


def _gather(b, sym, node, ins):
    ax = int(node["attrs"].get("axis", 0))
    # sym.take(data, indices, axis): the framework convention accepts
    # integer-typed index symbols directly
    return sym.take(ins[0], ins[1], axis=ax,
                    name=node["name"] or None)


def _conv_transpose(b, sym, node, ins):
    a = node["attrs"]
    kwargs = {"kernel": tuple(a.get("kernel_shape", ())),
              "stride": tuple(a.get("strides", (1, 1))),
              "num_group": int(a.get("group", 1)),
              "no_bias": len(ins) < 3}
    pads = a.get("pads")
    if pads:
        half = len(pads) // 2
        begin, end = tuple(pads[:half]), tuple(pads[half:])
        if begin != end:
            raise NotImplementedError(
                "ConvTranspose with asymmetric padding has no "
                "Deconvolution equivalent (pads=%r)" % (pads,))
        kwargs["pad"] = begin
    adj = a.get("output_padding")
    if adj:
        kwargs["adj"] = tuple(adj)
    dil = a.get("dilations")
    if dil:
        kwargs["dilate"] = tuple(dil)
    # num_filter from the weight initializer: (in, out/group, kh, kw)
    wname = node["inputs"][1]
    if wname not in b.inits:
        raise NotImplementedError(
            "ConvTranspose with a runtime-input weight (num_filter "
            "cannot be inferred without the initializer)")
    w = b.inits[wname]
    kwargs["num_filter"] = int(w.shape[1]) * kwargs["num_group"]
    return sym.Deconvolution(*ins, name=node["name"] or None, **kwargs)


def _lp_normalization(b, sym, node, ins):
    a = node["attrs"]
    if int(a.get("p", 2)) != 2 or int(a.get("axis", 1)) != 1:
        raise NotImplementedError(
            "LpNormalization import supports p=2, axis=1")
    return sym.L2Normalization(ins[0], mode="channel",
                               name=node["name"] or None)


def _multibox_detection(b, sym, node, ins):
    a = node["attrs"]
    kwargs = {}
    for k in ("nms_threshold", "threshold"):
        if k in a:
            kwargs[k] = float(a[k])
    for k in ("nms_topk", "background_id"):
        if k in a:
            kwargs[k] = int(a[k])
    for k in ("force_suppress", "clip"):
        if k in a:
            kwargs[k] = bool(int(a[k]))
    if "variances" in a:
        kwargs["variances"] = tuple(float(v) for v in a["variances"])
    return sym._contrib_MultiBoxDetection(*ins,
                                          name=node["name"] or None,
                                          **kwargs)


IMPORTERS = {
    "Conv": _conv,
    "ConvTranspose": _conv_transpose,
    "LpNormalization": _lp_normalization,
    # mxtpu custom-domain detection head (see mx2onnx
    # _multibox_detection: no opset-9 standard equivalent)
    "MXTPU_MultiBoxDetection": _multibox_detection,
    "BatchNormalization": _bn,
    "Relu": _act("relu"), "Sigmoid": _act("sigmoid"),
    "Tanh": _act("tanh"), "Softplus": _act("softrelu"),
    "Softsign": _act("softsign"),
    "MaxPool": _pool("MaxPool"), "AveragePool": _pool("AveragePool"),
    "GlobalMaxPool": _pool("GlobalMaxPool"),
    "GlobalAveragePool": _pool("GlobalAveragePool"),
    "Gemm": _gemm,
    "Flatten": _flatten,
    "Concat": lambda b, sym, node, ins: sym.Concat(
        *ins, dim=int(node["attrs"].get("axis", 1)),
        name=node["name"] or None),
    "Dropout": _dropout,
    "Softmax": _softmax,
    "Add": _binary("broadcast_add"), "Sub": _binary("broadcast_sub"),
    "Mul": _binary("broadcast_mul"), "Div": _binary("broadcast_div"),
    "Reshape": _reshape,
    "LRN": _lrn,
    "Pad": _pad,
    "Transpose": _transpose,
    "Clip": _clip,
    "LeakyRelu": _leaky, "Elu": lambda b, sym, node, ins:
        sym.LeakyReLU(ins[0], act_type="elu",
                      slope=float(node["attrs"].get("alpha", 1.0))),
    "PRelu": lambda b, sym, node, ins: sym.LeakyReLU(
        *ins, act_type="prelu"),
    "ReduceMean": _reduce_mean,
    "Slice": _slice,
    "Identity": _identity,
    # transformer-LM surface (mx2onnx Embedding/LayerNorm/attention
    # decompositions re-import through these primitives)
    "Cast": _cast,
    "Gather": _gather,
    "MatMul": lambda b, sym, node, ins: sym.linalg_gemm2(
        *ins, name=node["name"] or None),
    "Sqrt": lambda b, sym, node, ins: sym.sqrt(
        ins[0], name=node["name"] or None),
    "Shape": lambda b, sym, node, ins: sym.shape_array(
        ins[0], name=node["name"] or None),
}


def import_model(model_file):
    """Load an .onnx file -> (sym, arg_params, aux_params)
    (reference: onnx2mx/import_model.py:import_model)."""
    from ... import symbol as sym_mod
    from ... import nd

    with open(model_file, "rb") as f:
        m = P.parse_model(f.read())

    b = _Builder(dict(m["initializers"]))
    graph_inputs = [nm for nm, _, _ in m["inputs"]
                    if nm not in b.inits]
    for nm in graph_inputs:
        b.values[nm] = sym_mod.Variable(nm)

    for node in m["nodes"]:
        conv = IMPORTERS.get(node["op_type"])
        if conv is None:
            raise NotImplementedError(
                "no importer for ONNX op %r (node %r)"
                % (node["op_type"], node["name"]))
        ins = [b.sym_for(nm, sym_mod) for nm in node["inputs"]]
        out = conv(b, sym_mod, node, ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for nm, s in zip(node["outputs"], outs):
            b.values[nm] = s

    outputs = [b.values[nm] for nm, _, _ in m["outputs"]]
    out_sym = outputs[0] if len(outputs) == 1 else sym_mod.Group(outputs)

    arg_params, aux_params = {}, {}
    needed = set(out_sym.list_arguments()) | \
        set(out_sym.list_auxiliary_states())
    for nm, arr in b.inits.items():
        if nm in b.consumed or nm not in needed:
            continue
        target = aux_params if nm in b.aux_names or \
            nm in set(out_sym.list_auxiliary_states()) else arg_params
        target[nm] = nd.array(arr)
    return out_sym, arg_params, aux_params
