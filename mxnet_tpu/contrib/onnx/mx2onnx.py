"""Symbol-graph -> ONNX exporter.

Mirrors ``python/mxnet/contrib/onnx/mx2onnx/export_model.py`` (entry
point) + ``_op_translations.py`` (per-op converters), but serializes
through the in-repo protobuf codec (``_proto``) instead of the ``onnx``
wheel, which this image does not have.  Covers the full model-zoo CNN
surface (Convolution/BatchNorm/Activation/Pooling/FullyConnected/
Flatten/Concat/Dropout/broadcast & elemwise arithmetic/LRN/Pad/
Reshape/transpose/clip/LeakyReLU/softmax/mean/slice_axis) at opset 9.
"""

from __future__ import annotations

import json
import ast

import numpy as _np

from . import _proto as P


def _tuple(v, n=None):
    t = ast.literal_eval(v) if isinstance(v, str) else v
    if not isinstance(t, (tuple, list)):
        t = (t,) * (n or 1)
    return [int(x) for x in t]


def _bool(v):
    return str(v).lower() in ("true", "1")


def _pads2(pad):
    """MXNet symmetric (ph, pw) -> ONNX [ph, pw, ph, pw]."""
    p = _tuple(pad)
    return p + p


class _Ctx:
    """Conversion state: symbol-node index -> ONNX value names."""

    def __init__(self, params):
        self.params = params
        self.nodes = []            # serialized NodeProto bytes
        self.initializers = {}     # name -> np array
        self.inputs = []           # graph inputs (name, shape)
        self.out_name = {}         # (node_idx, out_idx) -> value name
        self.ncount = 0

    def emit(self, op_type, inputs, outputs, name=None, attrs=None):
        self.ncount += 1
        self.nodes.append(P.node(op_type, inputs, outputs,
                                 name or "%s_%d" % (op_type, self.ncount),
                                 attrs))

    def const(self, name, arr):
        self.initializers[name] = _np.asarray(arr)
        return name


def _conv(ctx, name, ins, attrs):
    a = {"kernel_shape": _tuple(attrs["kernel"]),
         "strides": _tuple(attrs.get("stride", "(1, 1)")),
         "pads": _pads2(attrs.get("pad", "(0, 0)")),
         "dilations": _tuple(attrs.get("dilate", "(1, 1)")),
         "group": int(attrs.get("num_group", 1))}
    ctx.emit("Conv", ins, [name], name, a)


def _bn(ctx, name, ins, attrs):
    # ins: data, gamma, beta, moving_mean, moving_var
    if _bool(attrs.get("fix_gamma", "False")) and ins[1] in \
            ctx.initializers:
        ctx.initializers[ins[1]] = _np.ones_like(ctx.initializers[ins[1]])
    ctx.emit("BatchNormalization", ins, [name], name,
             {"epsilon": float(attrs.get("eps", 1e-5)),
              "momentum": float(attrs.get("momentum", 0.9))})


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


def _activation(ctx, name, ins, attrs):
    ctx.emit(_ACT[attrs.get("act_type", "relu")], ins, [name], name)


def _pooling(ctx, name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if _bool(attrs.get("global_pool", "False")):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.emit(op, ins, [name], name)
        return
    kernel = _tuple(attrs["kernel"])
    stride = _tuple(attrs.get("stride", "(1, 1)"))
    pads = _pads2(attrs.get("pad", "(0, 0)"))
    if attrs.get("pooling_convention", "valid") == "full":
        # opset 9 has no ceil_mode; emulate ceil division with extra
        # END padding computed from the inferred input shape (max pool
        # only — padded cells would corrupt an average)
        if ptype != "max":
            raise NotImplementedError(
                "pooling_convention='full' export is supported for max "
                "pooling only at opset 9 (no ceil_mode)")
        shape = getattr(ctx, "value_shapes", {}).get(ins[0])
        if not shape:
            raise NotImplementedError(
                "pooling_convention='full' export needs input_shape "
                "for pad computation")
        nd_ = len(kernel)
        spatial = shape[-nd_:]
        pads = list(pads)
        for d in range(nd_):
            rem = (spatial[d] + 2 * pads[d] - kernel[d]) % stride[d]
            if rem:
                pads[nd_ + d] += stride[d] - rem
        pads = tuple(pads)
    a = {"kernel_shape": kernel, "strides": stride, "pads": pads}
    if ptype == "avg":
        a["count_include_pad"] = 1   # MXNet averages over padded cells
        ctx.emit("AveragePool", ins, [name], name, a)
    else:
        ctx.emit("MaxPool", ins, [name], name, a)


def _fc(ctx, name, ins, attrs):
    data = ins[0]
    if _bool(attrs.get("flatten", "True")):
        ctx.emit("Flatten", [data], [name + "_flat"], attrs=
                 {"axis": 1})
        data = name + "_flat"
        gemm_in = [data, ins[1]] + (ins[2:] if len(ins) > 2 else [])
        ctx.emit("Gemm", gemm_in, [name], name,
                 {"alpha": 1.0, "beta": 1.0, "transA": 0, "transB": 1})
        return
    # flatten=False keeps leading batch dims (rank >= 3) — opset-9
    # Gemm is strictly 2-D, so emit MatMul(x, W^T) (+ Add bias)
    wt = name + "_wT"
    ctx.emit("Transpose", [ins[1]], [wt], wt, {"perm": [1, 0]})
    if len(ins) > 2:
        mm = name + "_mm"
        ctx.emit("MatMul", [data, wt], [mm], mm)
        ctx.emit("Add", [mm, ins[2]], [name], name)
    else:
        ctx.emit("MatMul", [data, wt], [name], name)


def _binary(onnx_op):
    def f(ctx, name, ins, attrs):
        ctx.emit(onnx_op, ins, [name], name)
    return f


def _scalar(onnx_op, reverse=False):
    def f(ctx, name, ins, attrs):
        c = ctx.const(name + "_c",
                      _np.array(float(attrs["scalar"]), _np.float32))
        ctx.emit(onnx_op, [c, ins[0]] if reverse else [ins[0], c],
                 [name], name)
    return f


def _softmax(ctx, name, ins, attrs):
    ctx.emit("Softmax", ins[:1], [name], name,
             {"axis": int(attrs.get("axis", -1))})


def _dropout(ctx, name, ins, attrs):
    ctx.emit("Dropout", ins, [name], name,
             {"ratio": float(attrs.get("p", 0.5))})


def _reshape(ctx, name, ins, attrs):
    shape = _tuple(attrs["shape"])
    c = ctx.const(name + "_shape", _np.array(shape, _np.int64))
    ctx.emit("Reshape", [ins[0], c], [name], name)


def _lrn(ctx, name, ins, attrs):
    ctx.emit("LRN", ins, [name], name,
             {"alpha": float(attrs.get("alpha", 1e-4)),
              "beta": float(attrs.get("beta", 0.75)),
              "bias": float(attrs.get("knorm", 2.0)),
              "size": int(attrs["nsize"])})


def _pad(ctx, name, ins, attrs):
    pw = _tuple(attrs["pad_width"])
    nd2 = len(pw) // 2
    begins = [pw[2 * i] for i in range(nd2)]
    ends = [pw[2 * i + 1] for i in range(nd2)]
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect"}[attrs.get("mode", "constant")]
    ctx.emit("Pad", ins, [name], name,
             {"mode": mode, "pads": begins + ends,
              "value": float(attrs.get("constant_value", 0.0))})


def _transpose(ctx, name, ins, attrs):
    ctx.emit("Transpose", ins, [name], name,
             {"perm": _tuple(attrs.get("axes", "()"))})


def _clip(ctx, name, ins, attrs):
    ctx.emit("Clip", ins, [name], name,
             {"min": float(attrs["a_min"]), "max": float(attrs["a_max"])})


def _leaky(ctx, name, ins, attrs):
    act = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    if act == "leaky":
        ctx.emit("LeakyRelu", ins[:1], [name], name, {"alpha": slope})
    elif act == "elu":
        ctx.emit("Elu", ins[:1], [name], name, {"alpha": slope})
    elif act == "prelu":
        ctx.emit("PRelu", ins, [name], name)
    else:
        raise NotImplementedError("LeakyReLU act_type %r" % act)


def _mean(ctx, name, ins, attrs):
    ax = attrs.get("axis")
    a = {"keepdims": 1 if _bool(attrs.get("keepdims", "False")) else 0}
    if ax is not None:
        t = ast.literal_eval(ax) if isinstance(ax, str) else ax
        a["axes"] = list(t) if isinstance(t, (tuple, list)) else [int(t)]
    ctx.emit("ReduceMean", ins, [name], name, a)


def _slice_axis(ctx, name, ins, attrs):
    ax = int(attrs["axis"])
    begin = int(attrs["begin"])
    end = attrs.get("end")
    end = 2 ** 31 - 1 if end in (None, "None") else int(end)
    ctx.emit("Slice", ins, [name], name,
             {"axes": [ax], "starts": [begin], "ends": [end]})


def _flatten(ctx, name, ins, attrs):
    ctx.emit("Flatten", ins, [name], name, {"axis": 1})


def _identity(ctx, name, ins, attrs):
    ctx.emit("Identity", ins[:1], [name], name)


def _embedding(ctx, name, ins, attrs):
    # framework convention stores indices as floats; ONNX Gather needs
    # an integer tensor
    idx = name + "_idx"
    ctx.emit("Cast", [ins[0]], [idx], idx, {"to": P.DT_INT64})
    ctx.emit("Gather", [ins[1], idx], [name], name, {"axis": 0})


def _layer_norm(ctx, name, ins, attrs):
    """Decomposed (opset-9 has no LayerNormalization, and its reduce
    ops do not admit negative axes): the last-axis mean is a MatMul
    with a constant ones/D vector — rank-agnostic and opset-9 legal.
    D comes from the gamma initializer."""
    ax = int(attrs.get("axis", -1))
    if ax != -1:
        raise NotImplementedError("LayerNorm export supports axis=-1")
    x, g, b = ins
    if g not in ctx.initializers and g not in ctx.params:
        raise NotImplementedError(
            "LayerNorm export needs gamma as an initializer (to know "
            "the normalized width)")
    dim = int((ctx.initializers.get(g) if g in ctx.initializers
               else ctx.params[g]).shape[0])
    eps = float(attrs.get("eps", 1e-5))
    ones = ctx.const(name + "_avg",
                     _np.full((dim, 1), 1.0 / dim, _np.float32))
    mu, c, vr, ve, sd, nm_, sc = [name + s for s in
                                  ("_mu", "_c", "_var", "_ve", "_sd",
                                   "_n", "_sc")]
    ctx.emit("MatMul", [x, ones], [mu], mu)      # (..., 1) last-axis mean
    ctx.emit("Sub", [x, mu], [c], c)
    sq = name + "_sq"
    ctx.emit("Mul", [c, c], [sq], sq)
    ctx.emit("MatMul", [sq, ones], [vr], vr)
    ctx.emit("Add", [vr, ctx.const(name + "_eps",
                                   _np.float32(eps))], [ve], ve)
    ctx.emit("Sqrt", [ve], [sd], sd)
    ctx.emit("Div", [c, sd], [nm_], nm_)
    ctx.emit("Mul", [nm_, g], [sc], sc)
    ctx.emit("Add", [sc, b], [name], name)


def _slice_like(ctx, name, ins, attrs):
    axes = _tuple(attrs.get("axes", "(0,)"))
    if axes != [1] or not getattr(ctx, "input_shapes", None):
        raise NotImplementedError(
            "slice_like export supports axes=(1,) with a known input "
            "shape (the positional-table pattern)")
    seq = int(ctx.input_shapes[0][1])
    ctx.emit("Slice", [ins[0]], [name], name,
             {"axes": [1], "starts": [0], "ends": [seq]})


def _dot_product_attention(ctx, name, ins, attrs):
    """Scaled dot-product attention decomposition: MatMul/Softmax/
    MatMul with a dynamic 1/sqrt(d) scale (Shape->Gather->Sqrt) and,
    for causal, a constant additive mask at the export seq length."""
    q, k, v = ins
    causal = _bool(attrs.get("causal", "False"))
    kt = name + "_kt"
    ctx.emit("Transpose", [k], [kt], kt, {"perm": [0, 1, 3, 2]})
    s0 = name + "_qk"
    ctx.emit("MatMul", [q, kt], [s0], s0)
    sm_scale = attrs.get("sm_scale")
    if sm_scale not in (None, "None"):
        cur = name + "_scaled"
        ctx.emit("Mul", [s0, ctx.const(name + "_scale",
                                       _np.float32(float(sm_scale)))],
                 [cur], cur)
    else:
        shp, didx, dfl, dsq = [name + s for s in
                               ("_shape", "_d", "_df", "_sqrtd")]
        ctx.emit("Shape", [q], [shp], shp)
        ctx.emit("Gather", [shp, ctx.const(name + "_didx",
                                           _np.array([3], _np.int64))],
                 [didx], didx, {"axis": 0})
        ctx.emit("Cast", [didx], [dfl], dfl, {"to": P.DT_FLOAT})
        ctx.emit("Sqrt", [dfl], [dsq], dsq)
        cur = name + "_scaled"
        ctx.emit("Div", [s0, dsq], [cur], cur)
    if causal:
        shapes = getattr(ctx, "input_shapes", None)
        if not shapes or len(shapes[0]) != 2:
            raise NotImplementedError(
                "causal attention export supports square causal "
                "SELF-attention with a rank-2 (batch, seq) token input "
                "shape — the additive mask is a constant at that "
                "sequence length")
        seq = int(shapes[0][1])
        mask = _np.triu(_np.full((seq, seq), -1e9, _np.float32), 1)
        am = name + "_masked"
        ctx.emit("Add", [cur, ctx.const(name + "_mask", mask)],
                 [am], am)
        cur = am
    p = name + "_p"
    ctx.emit("Softmax", [cur], [p], p, {"axis": 3})
    ctx.emit("MatMul", [p, v], [name], name)


def _deconv(ctx, name, ins, attrs):
    a = {"kernel_shape": _tuple(attrs["kernel"]),
         "strides": _tuple(attrs.get("stride", "(1, 1)")),
         "pads": _pads2(attrs.get("pad", "(0, 0)")),
         "output_padding": _tuple(attrs.get("adj", "(0, 0)")),
         "dilations": _tuple(attrs.get("dilate", "(1, 1)")),
         "group": int(attrs.get("num_group", 1))}
    ctx.emit("ConvTranspose", ins, [name], name, a)


def _l2_normalization(ctx, name, ins, attrs):
    mode = attrs.get("mode", "instance")
    if mode != "channel":
        raise NotImplementedError(
            "L2Normalization export supports mode='channel' "
            "(LpNormalization axis=1); got %r" % mode)
    ctx.emit("LpNormalization", ins, [name], name, {"axis": 1, "p": 2})


def _multibox_prior(ctx, name, ins, attrs):
    """Anchors depend only on the feature-map geometry, which is fixed
    at export time — bake them as a constant initializer by running the
    real op (ops/detection.py) on the inferred shape.  This is the
    standard way SSD exports its priors (the reference exporter does
    the same shape-driven materialization)."""
    fshape = getattr(ctx, "value_shapes", {}).get(ins[0])
    if not fshape:
        raise NotImplementedError(
            "MultiBoxPrior export needs input_shape for anchor "
            "materialization")
    import jax.numpy as jnp
    from ...ops.registry import get_op
    params = {}
    for k in ("sizes", "ratios", "steps", "offsets", "clip"):
        if k in attrs:
            v = attrs[k]
            params[k] = ast.literal_eval(v) if isinstance(v, str) else v
    anchors = get_op("_contrib_MultiBoxPrior").fn(
        jnp.zeros(tuple(int(s) for s in fshape), _np.float32), **params)
    ctx.const(name, _np.asarray(anchors))


def _multibox_detection(ctx, name, ins, attrs):
    """Decode+NMS head.  Standard ONNX has no opset-9 equivalent
    (NonMaxSuppression is opset 10+), so this exports as an op in the
    'mxtpu' custom domain: round-trips through this package's importer,
    clearly rejected by generic runtimes instead of silently wrong."""
    a = {}
    for k in ("nms_threshold", "threshold"):
        if k in attrs:
            a[k] = float(attrs[k])
    for k in ("nms_topk", "background_id"):
        if k in attrs:
            a[k] = int(attrs[k])
    for k in ("force_suppress", "clip"):
        if k in attrs:
            a[k] = int(_bool(attrs[k]))
    if "variances" in attrs:
        v = attrs["variances"]
        a["variances"] = [float(x) for x in
                          (ast.literal_eval(v) if isinstance(v, str)
                           else v)]
    ctx.emit("MXTPU_MultiBoxDetection", ins, [name], name, a)


CONVERTERS = {
    "Convolution": _conv,
    "Deconvolution": _deconv,
    "L2Normalization": _l2_normalization,
    "_contrib_MultiBoxPrior": _multibox_prior,
    "_contrib_MultiBoxDetection": _multibox_detection,
    "BatchNorm": _bn,
    "Activation": _activation,
    "Pooling": _pooling,
    "FullyConnected": _fc,
    "Flatten": _flatten,
    "flatten": _flatten,
    "Concat": lambda ctx, name, ins, attrs: ctx.emit(
        "Concat", ins, [name], name,
        {"axis": int(attrs.get("dim", 1))}),
    "concat": lambda ctx, name, ins, attrs: ctx.emit(
        "Concat", ins, [name], name,
        {"axis": int(attrs.get("dim", 1))}),
    "Dropout": _dropout,
    "softmax": _softmax,
    "SoftmaxOutput": _softmax,
    "SoftmaxActivation": _softmax,
    "elemwise_add": _binary("Add"), "broadcast_add": _binary("Add"),
    "_plus": _binary("Add"),
    "elemwise_sub": _binary("Sub"), "broadcast_sub": _binary("Sub"),
    "elemwise_mul": _binary("Mul"), "broadcast_mul": _binary("Mul"),
    "elemwise_div": _binary("Div"), "broadcast_div": _binary("Div"),
    "_plus_scalar": _scalar("Add"),
    "_minus_scalar": _scalar("Sub"),
    "_mul_scalar": _scalar("Mul"),
    "_div_scalar": _scalar("Div"),
    "_rminus_scalar": _scalar("Sub", reverse=True),
    "_rdiv_scalar": _scalar("Div", reverse=True),
    "Reshape": _reshape, "reshape": _reshape,
    "LRN": _lrn,
    "Pad": _pad, "pad": _pad,
    "transpose": _transpose,
    "clip": _clip,
    "LeakyReLU": _leaky,
    "mean": _mean,
    "slice_axis": _slice_axis,
    "identity": _identity, "_copy": _identity, "BlockGrad": _identity,
    "Embedding": _embedding,
    "LayerNorm": _layer_norm,
    "slice_like": _slice_like,
    "_contrib_DotProductAttention": _dot_product_attention,
}


def export_model(sym, params, input_shape=None, input_type=_np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol (or saved symbol json path) + params to ONNX.

    Mirrors the reference signature
    (mx2onnx/export_model.py:export_model).  ``params`` may be a dict of
    NDArray/ndarray (arg+aux merged, optionally ``arg:``/``aux:``
    prefixed as in saved .params files) or a path to one.  Returns the
    output file path.

    Shape caveat: converters that need a concrete length at export time
    (the causal-attention additive mask, slice_like positional-table
    bounds) bake ``input_shape``'s sequence length into the graph as
    constants, so the exported model only accepts inputs of that exact
    sequence length (batch stays dynamic).  The traced input shapes are
    recorded in the ModelProto ``doc_string`` so a consumer hitting a
    downstream broadcast error can see the expected shapes.
    """
    from ...symbol import Symbol, load as sym_load
    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(params, str):
        from ...ndarray import load as nd_load
        params = nd_load(params)
    np_params = {}
    for k, v in params.items():
        k = k.split(":", 1)[1] if ":" in k else k
        np_params[k] = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy")
                                   else v)
    if isinstance(input_shape, tuple):
        input_shape = [input_shape]

    g = json.loads(sym.tojson()) if isinstance(sym, Symbol) else sym
    nodes = g["nodes"]
    heads = [tuple(h[:2]) for h in g["heads"]]

    ctx = _Ctx(np_params)
    ctx.input_shapes = input_shape  # slice_like / causal-mask exports
    # per-value shapes for converters that materialize shape-dependent
    # constants (MultiBoxPrior anchors): internal-output inference over
    # the ORIGINAL symbol, keyed by the producing node's name
    ctx.value_shapes = {}
    if isinstance(sym, Symbol) and input_shape:
        data_names = [n["name"] for n in g["nodes"]
                      if n["op"] == "null" and
                      n["name"] not in np_params]
        feed = {nm: tuple(s) for nm, s in zip(data_names, input_shape)}
        try:
            ints = sym.get_internals()
            _, out_shapes, _ = ints.infer_shape_partial(**feed)
            for nm, shp in zip(ints.list_outputs(), out_shapes):
                if shp:
                    key = nm[:-7] if nm.endswith("_output") else nm
                    ctx.value_shapes[key] = tuple(shp)
            ctx.value_shapes.update(feed)
        except Exception:
            pass  # shape-needing converters raise their own error
    dtype = _np.dtype(input_type)
    elem = P._NP_TO_DT[dtype.name]
    # uniquify node names: duplicate names in the symbol JSON would
    # silently clobber values in the ONNX graph's flat namespace
    seen = {}
    uniq = {}
    for i, n in enumerate(nodes):
        nm = n["name"]
        if nm in seen:
            seen[nm] += 1
            uniq[i] = "%s_%d" % (nm, seen[nm])
        else:
            seen[nm] = 0
            uniq[i] = nm
    # duplicate node names make the name-keyed shape map ambiguous
    # (and converters look up by uniquified name anyway): drop them so
    # a shape-needing converter raises its clear error instead of
    # using the wrong duplicate's shape
    for nm, cnt in seen.items():
        if cnt > 0:
            ctx.value_shapes.pop(nm, None)
    data_i = 0
    for i, n in enumerate(nodes):
        if n["op"] != "null":
            continue
        name = n["name"]
        ctx.out_name[(i, 0)] = name
        if name in np_params:
            ctx.initializers[name] = np_params[name]
        else:
            shape = (input_shape[data_i] if input_shape and
                     data_i < len(input_shape) else ["N"])
            ctx.inputs.append((name, shape))
            data_i += 1

    for i, n in enumerate(nodes):
        if n["op"] == "null":
            continue
        ins = [ctx.out_name[tuple(e[:2])] for e in n["inputs"]]
        conv = CONVERTERS.get(n["op"])
        if conv is None:
            raise NotImplementedError(
                "no ONNX converter for op %r (node %r)"
                % (n["op"], n["name"]))
        conv(ctx, uniq[i], ins, n.get("attrs", {}))
        ctx.out_name[(i, 0)] = uniq[i]
        if verbose:
            print("converted %s %s" % (n["op"], n["name"]))

    out_infos = []
    for k, (ni, oi) in enumerate(heads):
        out_infos.append(P.value_info(ctx.out_name[(ni, oi)], elem,
                                      ["N"]))
    in_infos = [P.value_info(nm, elem, shp) for nm, shp in ctx.inputs]
    # opset-9 style: initializers are also declared as graph inputs
    for nm, arr in ctx.initializers.items():
        in_infos.append(P.value_info(nm, P._NP_TO_DT[arr.dtype.name],
                                     list(arr.shape)))
    inits = [P.tensor(nm, arr) for nm, arr in ctx.initializers.items()]
    gb = P.graph(ctx.nodes, "mxnet_tpu_model", inits, in_infos,
                 out_infos)
    doc = ("traced input shapes: %r (constants such as causal masks are "
           "baked at these lengths)" % (input_shape,)) if input_shape \
        else None
    blob = P.model(gb, doc_string=doc)
    from ...resilience.checkpoint import atomic_write
    atomic_write(onnx_file_path, blob)
    return onnx_file_path
