"""Minimal ONNX protobuf wire codec.

The ``onnx`` wheel does not exist in this image, but an .onnx file is
just a serialized ``ModelProto`` — and protobuf's wire format is three
primitives (varints, 64/32-bit scalars, length-delimited blobs).  This
module implements exactly the message subset the exporter/importer
need, with the field numbers from the public ``onnx/onnx.proto`` schema
(stable since IR version 3).  ``tools`` like ``protoc
--decode=onnx.ModelProto`` read the output directly (see
tests/test_onnx.py), and files produced by real onnx installations
parse with the decoder here.

Reference entry points mirrored:
``python/mxnet/contrib/onnx/mx2onnx/export_model.py`` and
``onnx2mx/import_model.py``.
"""

from __future__ import annotations

import struct

import numpy as _np

# ONNX TensorProto.DataType enum values
DT_FLOAT = 1
DT_UINT8 = 2
DT_INT8 = 3
DT_INT32 = 6
DT_INT64 = 7
DT_BOOL = 9
DT_FLOAT16 = 10
DT_DOUBLE = 11
DT_BFLOAT16 = 16

_NP_TO_DT = {
    "float32": DT_FLOAT, "uint8": DT_UINT8, "int8": DT_INT8,
    "int32": DT_INT32, "int64": DT_INT64, "bool": DT_BOOL,
    "float16": DT_FLOAT16, "float64": DT_DOUBLE, "bfloat16": DT_BFLOAT16,
}
DT_TO_NP = {v: k for k, v in _NP_TO_DT.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------

def _varint(n):
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(field, value):
    return _varint(field << 3 | 0) + _varint(value)


def _field_bytes(field, payload):
    if isinstance(payload, str):
        payload = payload.encode()
    return _varint(field << 3 | 2) + _varint(len(payload)) + bytes(payload)


def _field_float(field, value):
    return _varint(field << 3 | 5) + struct.pack("<f", value)


# ---------------------------------------------------------------------------
# message builders (each returns serialized bytes)
# ---------------------------------------------------------------------------

def tensor(name, arr):
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = _np.ascontiguousarray(arr)
    dt = _NP_TO_DT[arr.dtype.name]
    out = b"".join(_field_varint(1, int(d)) for d in arr.shape)
    out += _field_varint(2, dt)
    out += _field_bytes(8, name)
    out += _field_bytes(9, arr.tobytes())
    return out


def attribute(name, value):
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    strings=9, type=20."""
    out = _field_bytes(1, name)
    if isinstance(value, bool):
        out += _field_varint(3, int(value)) + _field_varint(20, AT_INT)
    elif isinstance(value, int):
        out += _field_varint(3, value) + _field_varint(20, AT_INT)
    elif isinstance(value, float):
        out += _field_float(2, value) + _field_varint(20, AT_FLOAT)
    elif isinstance(value, (str, bytes)):
        out += _field_bytes(4, value) + _field_varint(20, AT_STRING)
    elif isinstance(value, _np.ndarray):
        out += _field_bytes(5, tensor(name + "_t", value))
        out += _field_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                out += _field_float(7, v)
            out += _field_varint(20, AT_FLOATS)
        elif value and isinstance(value[0], (str, bytes)):
            for v in value:
                out += _field_bytes(9, v)
            out += _field_varint(20, AT_STRINGS)
        else:
            for v in value:
                out += _field_varint(8, int(v))
            out += _field_varint(20, AT_INTS)
    else:
        raise TypeError("unsupported attribute %r=%r" % (name, value))
    return out


def node(op_type, inputs, outputs, name="", attrs=None):
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(_field_bytes(1, i) for i in inputs)
    out += b"".join(_field_bytes(2, o) for o in outputs)
    if name:
        out += _field_bytes(3, name)
    out += _field_bytes(4, op_type)
    for k, v in (attrs or {}).items():
        out += _field_bytes(5, attribute(k, v))
    return out


def _tensor_shape(shape):
    """TensorShapeProto: dim=1; Dimension: dim_value=1, dim_param=2."""
    out = b""
    for d in shape:
        if isinstance(d, str):
            out += _field_bytes(1, _field_bytes(2, d))
        else:
            out += _field_bytes(1, _field_varint(1, int(d)))
    return out


def value_info(name, elem_type, shape):
    """ValueInfoProto: name=1, type=2; TypeProto: tensor_type=1;
    TypeProto.Tensor: elem_type=1, shape=2."""
    tt = _field_varint(1, elem_type) + _field_bytes(2,
                                                   _tensor_shape(shape))
    return _field_bytes(1, name) + _field_bytes(2, _field_bytes(1, tt))


def graph(nodes, name, initializers, inputs, outputs):
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(_field_bytes(1, n) for n in nodes)
    out += _field_bytes(2, name)
    out += b"".join(_field_bytes(5, t) for t in initializers)
    out += b"".join(_field_bytes(11, vi) for vi in inputs)
    out += b"".join(_field_bytes(12, vi) for vi in outputs)
    return out


def model(graph_bytes, opset=9, producer="mxnet_tpu",
          producer_version="0.4", ir_version=4, doc_string=None):
    """ModelProto: ir_version=1, producer_name=2, producer_version=3,
    doc_string=6, graph=7, opset_import=8; OperatorSetIdProto:
    domain=1, version=2."""
    out = _field_varint(1, ir_version)
    out += _field_bytes(2, producer)
    out += _field_bytes(3, producer_version)
    if doc_string:
        out += _field_bytes(6, doc_string)
    out += _field_bytes(7, graph_bytes)
    out += _field_bytes(8, _field_bytes(1, "") + _field_varint(2, opset))
    return out


# ---------------------------------------------------------------------------
# decoder: bytes -> {field: [raw values]} trees
# ---------------------------------------------------------------------------

def decode_fields(buf):
    """One-level protobuf decode: {field_number: [values]} where varint
    fields give ints and length-delimited fields give memoryviews."""
    mv = memoryview(buf)
    out = {}
    off = 0
    n = len(mv)
    while off < n:
        key = 0
        shift = 0
        while True:
            b = mv[off]
            off += 1
            key |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wire = key >> 3, key & 7
        if wire == 0:
            val = 0
            shift = 0
            while True:
                b = mv[off]
                off += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = mv[off]
                off += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            val = mv[off:off + ln]
            off += ln
        elif wire == 5:
            val = struct.unpack_from("<f", mv, off)[0]
            off += 4
        elif wire == 1:
            val = struct.unpack_from("<d", mv, off)[0]
            off += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        out.setdefault(field, []).append(val)
    return out


def _sint(v):
    """varint -> signed int64 (two's complement)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_tensor(buf):
    """TensorProto bytes -> (name, numpy array)."""
    f = decode_fields(buf)
    dims = [_sint(d) for d in f.get(1, [])]
    dt = f[2][0]
    name = bytes(f[8][0]).decode() if 8 in f else ""
    np_dt = _np.dtype(DT_TO_NP[dt])
    if 9 in f:
        arr = _np.frombuffer(bytes(f[9][0]), np_dt).reshape(dims)
    elif dt == DT_FLOAT and 4 in f:
        arr = _np.array(f[4], _np.float32).reshape(dims)
    elif dt in (DT_INT32, DT_BOOL) and 5 in f:
        arr = _np.array([_sint(v) for v in f[5]], np_dt).reshape(dims)
    elif dt == DT_INT64 and 7 in f:
        arr = _np.array([_sint(v) for v in f[7]], _np.int64).reshape(dims)
    else:
        arr = _np.zeros(dims, np_dt)
    return name, arr


def parse_attribute(buf):
    """AttributeProto bytes -> (name, python value)."""
    f = decode_fields(buf)
    name = bytes(f[1][0]).decode()
    at = f.get(20, [0])[0]
    if at == AT_FLOAT or (at == 0 and 2 in f):
        return name, float(f[2][0])
    if at == AT_INT or (at == 0 and 3 in f):
        return name, _sint(f[3][0])
    if at == AT_STRING or (at == 0 and 4 in f):
        return name, bytes(f[4][0]).decode()
    if at == AT_TENSOR or (at == 0 and 5 in f):
        return name, parse_tensor(f[5][0])[1]
    if at == AT_FLOATS:
        return name, [float(v) for v in f.get(7, [])]
    if at == AT_INTS:
        return name, [_sint(v) for v in f.get(8, [])]
    if at == AT_STRINGS:
        return name, [bytes(v).decode() for v in f.get(9, [])]
    raise ValueError("unsupported attribute type %d for %r" % (at, name))


def parse_node(buf):
    """NodeProto bytes -> dict(op_type, name, inputs, outputs, attrs)."""
    f = decode_fields(buf)
    return {
        "inputs": [bytes(v).decode() for v in f.get(1, [])],
        "outputs": [bytes(v).decode() for v in f.get(2, [])],
        "name": bytes(f[3][0]).decode() if 3 in f else "",
        "op_type": bytes(f[4][0]).decode(),
        "attrs": dict(parse_attribute(a) for a in f.get(5, [])),
    }


def parse_value_info(buf):
    """ValueInfoProto bytes -> (name, elem_type, shape)."""
    f = decode_fields(buf)
    name = bytes(f[1][0]).decode()
    elem, shape = DT_FLOAT, []
    if 2 in f:
        tp = decode_fields(f[2][0])
        if 1 in tp:
            tt = decode_fields(tp[1][0])
            elem = tt.get(1, [DT_FLOAT])[0]
            if 2 in tt:
                for dim in decode_fields(tt[2][0]).get(1, []):
                    df = decode_fields(dim)
                    if 1 in df:
                        shape.append(_sint(df[1][0]))
                    elif 2 in df:
                        shape.append(bytes(df[2][0]).decode())
                    else:
                        shape.append(0)
    return name, elem, shape


def parse_model(buf):
    """ModelProto bytes -> dict with graph pieces decoded."""
    f = decode_fields(buf)
    g = decode_fields(f[7][0])
    return {
        "ir_version": f.get(1, [0])[0],
        "producer": bytes(f[2][0]).decode() if 2 in f else "",
        "opset": max((decode_fields(o).get(2, [0])[0]
                      for o in f.get(8, [])), default=0),
        "nodes": [parse_node(n) for n in g.get(1, [])],
        "name": bytes(g[2][0]).decode() if 2 in g else "",
        "initializers": dict(parse_tensor(t) for t in g.get(5, [])),
        "inputs": [parse_value_info(v) for v in g.get(11, [])],
        "outputs": [parse_value_info(v) for v in g.get(12, [])],
    }
