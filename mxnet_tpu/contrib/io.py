"""Contrib IO (reference: python/mxnet/contrib/io.py —
DataLoaderIter: wrap a Gluon DataLoader as a DataIter for Module.fit)."""

from __future__ import annotations

from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Present a gluon DataLoader as a Module-compatible DataIter."""

    def __init__(self, loader, data_name="data", label_name="softmax_label"):
        super().__init__()
        self._loader = loader
        self._iter = None
        self._data_name = data_name
        self._label_name = label_name
        first = next(iter(loader))
        data, label = self._split(first)
        self.batch_size = data.shape[0]
        self.provide_data = [DataDesc(data_name, data.shape, data.dtype)]
        self.provide_label = (
            [DataDesc(label_name, label.shape, label.dtype)]
            if label is not None else [])
        self.reset()

    def _split(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return batch[0], batch[1]
            return batch[0], None
        return batch, None

    def reset(self):
        self._iter = iter(self._loader)

    def next(self):
        try:
            batch = next(self._iter)
        except StopIteration:
            raise StopIteration
        data, label = self._split(batch)
        return DataBatch(data=[data],
                         label=[label] if label is not None else [],
                         pad=0)
