"""SVRGModule — stochastic variance-reduced gradient training
(reference: contrib/svrg_optimization/svrg_module.py:30 + the
_SVRGOptimizer grad rewrite in svrg_optimizer.py).

Every ``update_freq`` epochs the module snapshots the weights and
computes the full-dataset gradient at the snapshot; each step then
applies the variance-reduced gradient

    g = g_i(w) - g_i(w_snap) + mu,     mu = full gradient at w_snap

where g_i(w_snap) is recomputed on the current batch through an
auxiliary module bound to the same symbol."""

from __future__ import annotations

from ... import ndarray as nd
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, **kwargs)
        self._param_dict = None   # mu: full grads at the snapshot
        self._ctx_len = 1

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, **kwargs)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, **kwargs)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        super().init_params(initializer, arg_params, aux_params,
                            allow_missing, force_init, allow_extra)
        arg, aux = self.get_params()
        self._mod_aux.init_params(
            initializer, arg_params={k: v.copy() for k, v in arg.items()},
            aux_params={k: v.copy() for k, v in aux.items()},
            allow_missing=False, force_init=True)

    def update_full_grads(self, train_data):
        """Snapshot current weights into the aux module and accumulate
        the full-dataset gradient there (reference: svrg_module.py
        update_full_grads)."""
        arg, aux = self.get_params()
        self._mod_aux.set_params({k: v.copy() for k, v in arg.items()},
                                 {k: v.copy() for k, v in aux.items()})
        group = self._mod_aux._exec_group
        accum = {name: None for name in group.param_names
                 if group.grad_req[name] != "null"}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            group.reduce_grads()
            ex0 = group.execs[0]
            for name in accum:
                g = ex0.grad_dict[name].copy()
                accum[name] = g if accum[name] is None else accum[name] + g
            nbatch += 1
        train_data.reset()
        self._param_dict = {
            name: (g / nbatch if g is not None else None)
            for name, g in accum.items()}

    def update(self):
        """Apply the SVRG-adjusted gradient then the optimizer step."""
        if self._param_dict is not None:
            group = self._exec_group
            aux_group = self._mod_aux._exec_group
            n_exec = len(group.execs)
            for ex, aux_ex in zip(group.execs, aux_group.execs):
                for name, mu in self._param_dict.items():
                    if mu is None:
                        continue
                    # g <- g - g_snap + mu  (variance reduction); execs
                    # are summed downstream, so mu is spread across them
                    ex.grad_dict[name][:] = (
                        ex.grad_dict[name] - aux_ex.grad_dict[name]
                        + mu / n_exec)
        super().update()

    def forward_backward(self, data_batch):
        super().forward_backward(data_batch)
        if self._param_dict is not None:
            # batch gradient at the snapshot weights, same batch
            self._mod_aux.forward_backward(data_batch)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            validation_metric=None, initializer=None, arg_params=None,
            aux_params=None, allow_missing=False, force_rebind=False,
            force_init=False, epoch_end_callback=None, **kwargs):
        """Module.fit with a full-gradient refresh before epoch 0 and
        every update_freq epochs after (reference: svrg_module.py fit)."""
        from ... import initializer as init_mod
        # bind + init here so the epoch-0 snapshot can run before the
        # first training epoch (the base fit re-binds idempotently)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(
            initializer=initializer or init_mod.Uniform(0.01),
            arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init)
        self.update_full_grads(train_data)

        svrg_self = self

        def _refresh(epoch, *cb_args):
            if (epoch + 1) % svrg_self.update_freq == 0:
                svrg_self.update_full_grads(train_data)
            if epoch_end_callback is not None:
                cbs = (epoch_end_callback
                       if isinstance(epoch_end_callback, (list, tuple))
                       else [epoch_end_callback])
                for cb in cbs:
                    cb(epoch, *cb_args)

        super().fit(train_data, eval_data, eval_metric,
                    validation_metric=validation_metric,
                    epoch_end_callback=_refresh, **kwargs)
