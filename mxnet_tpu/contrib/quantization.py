"""INT8 model quantization: graph rewrite + calibration driver.

Reference: ``src/operator/quantization/quantize_graph_pass.cc:119``
(QuantizeGraph inserts quantize/dequantize pairs around ops carrying the
FQuantizedOp attr, ``:92-96``) and the Python driver
``python/mxnet/contrib/quantization.py`` (quantize_model with
calib_mode none/naive).

TPU-native mapping: quantized Convolution/FullyConnected run int8 x int8
-> int32 on the MXU (``ops/quantization.py``); the rewrite inserts
``_contrib_quantize`` on activations (either with calibrated min/max
parameters — calib_mode='naive' — or with in-graph dynamic min/max —
calib_mode='none') and a ``_contrib_dequantize`` on the int32
accumulator; weights are quantized OFFLINE to int8 parameters, so the
serialized quantized model carries int8 weights exactly like the
reference's.
"""

from __future__ import annotations

import logging

import numpy as _np

from .. import ndarray as nd
from .. import symbol as S
from ..symbol.symbol import Node, Symbol

__all__ = ["quantize_symbol", "quantize_model"]

_QUANTIZABLE = ("Convolution", "FullyConnected")


def _entry_symbol(entry):
    return Symbol([entry])


def quantize_symbol(sym, excluded_sym_names=(), quantized_dtype="int8",
                    calib_mode="naive"):
    """Rewrite *sym*, quantizing every Convolution/FullyConnected not in
    *excluded_sym_names*.

    Returns (qsym, calib_points) where calib_points maps
    ``<node name>_data`` -> the ORIGINAL graph entry feeding that node
    (for offline range collection) — empty for calib_mode='none', where
    ranges are computed in-graph per batch (dynamic quantization).
    """
    assert quantized_dtype == "int8", "int8 is the TPU MXU path"
    excluded = set(excluded_sym_names)
    order = sym._topo()
    entry_map = {}       # (id(orig_node), out_idx) -> new entry
    calib_points = {}

    def mapped(entry):
        node, idx = entry
        if node.is_var:
            return (node, idx)
        return entry_map[(id(node), idx)]

    for node in order:
        if node.is_var:
            continue
        new_inputs = [mapped(e) for e in node.inputs]
        if node.op.name in _QUANTIZABLE and node.name not in excluded:
            data = _entry_symbol(new_inputs[0])
            worig = node.inputs[1][0]           # weight var node
            has_bias = not node.params.get("no_bias", False) and \
                len(node.inputs) > 2
            # activation ranges are SYMMETRIC (-M, M): the int32
            # accumulator's real value is then exactly
            # q_d * q_w * (Md/127) * (Mw/127) with no zero-point
            # correction term (the reference's MKLDNN path carries a
            # compensation tensor instead; symmetric is the clean MXU
            # mapping)
            if calib_mode == "none":
                m = S.max(S.abs(data))
                dmin = 0.0 - m
                dmax = m
            else:
                dmin = S.var("%s_data_min" % node.name)
                dmax = S.var("%s_data_max" % node.name)
                calib_points["%s_data" % node.name] = node.inputs[0]
            dq = S._contrib_quantize(data, dmin, dmax, out_type="int8",
                                     name="%s_quantize" % node.name)
            wq = S.var("%s_quantized" % worig.name)
            wmin = S.var("%s_min" % worig.name)
            wmax = S.var("%s_max" % worig.name)
            if node.op.name == "Convolution":
                p = node.params
                q = S._contrib_quantized_conv(
                    dq[0], wq, dq[1], dq[2], wmin, wmax,
                    kernel=p.get("kernel"), stride=p.get("stride"),
                    pad=p.get("pad"), dilate=p.get("dilate"),
                    num_filter=p.get("num_filter"),
                    num_group=p.get("num_group", 1),
                    name="%s_quantized" % node.name)
                out = S._contrib_dequantize(
                    q[0], q[1], q[2], name="%s_dequantize" % node.name)
                if has_bias:
                    bias = _entry_symbol(new_inputs[2])
                    out = S.broadcast_add(
                        out, S.reshape(bias, shape=(1, -1, 1, 1)))
            else:
                p = node.params
                q = S._contrib_quantized_fully_connected(
                    dq[0], wq, dq[1], dq[2], wmin, wmax,
                    num_hidden=p.get("num_hidden"),
                    flatten=p.get("flatten", True),
                    name="%s_quantized" % node.name)
                out = S._contrib_dequantize(
                    q[0], q[1], q[2], name="%s_dequantize" % node.name)
                if has_bias:
                    bias = _entry_symbol(new_inputs[2])
                    out = S.broadcast_add(out,
                                          S.reshape(bias, shape=(1, -1)))
            entry_map[(id(node), 0)] = out._outputs[0]
        else:
            new_node = Node(node.op, node.name, params=node.params,
                            inputs=new_inputs, attrs=node.attrs)
            for i in range(node.num_outputs()):
                entry_map[(id(node), i)] = (new_node, i)

    qsym = Symbol([mapped(e) for e in sym._outputs])
    return qsym, calib_points


def _collect_naive_ranges(sym, calib_points, arg_params, aux_params,
                          calib_data, data_names, num_calib_examples,
                          label_names=()):
    """Global min/max per calibration point over the calib batches
    (reference: quantization.py _LayerOutputMinMaxCollector,
    calib_mode='naive')."""
    group = S.Group([_entry_symbol(e) for e in calib_points.values()])
    names = list(calib_points)
    th = {n: (_np.inf, -_np.inf) for n in names}
    seen = 0
    exe = None
    calib_data.reset()
    for batch in calib_data:
        feeds = {}
        for dn, arr in zip(data_names, batch.data):
            feeds[dn] = arr
        if batch.label:
            for ln, arr in zip(label_names, batch.label):
                feeds[ln] = arr
        if exe is None:
            # bind ONCE: each bind creates fresh jitted closures, so a
            # per-batch bind would recompile the collection graph every
            # batch
            exe = group.bind(args={**dict(arg_params), **feeds},
                             aux_states=dict(aux_params or {}))
        outs = exe.forward(is_train=False, **feeds)
        for n, o in zip(names, outs):
            v = o.asnumpy()
            lo, hi = th[n]
            th[n] = (min(lo, float(v.min())), max(hi, float(v.max())))
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return th


def _quantize_weights(sym, arg_params):
    """Offline symmetric int8 weight quantization for every
    '*_quantized' weight var the rewrite introduced."""
    qargs = dict(arg_params)
    still_needed = set(sym.list_arguments())
    for name in still_needed:
        if name.endswith("_quantized") and name[:-10] in arg_params:
            w = arg_params[name[:-10]].asnumpy()
            m = float(_np.abs(w).max()) or 1e-8
            q = _np.clip(_np.round(w * 127.0 / m), -127, 127) \
                .astype(_np.int8)
            qargs[name] = nd.array(q)
            qargs[name[:-10] + "_min"] = nd.array(
                _np.asarray(-m, _np.float32))
            qargs[name[:-10] + "_max"] = nd.array(
                _np.asarray(m, _np.float32))
            if name[:-10] not in still_needed:
                # the fp32 weight may still be consumed by an excluded
                # layer (tied weights) — only drop it when unused
                del qargs[name[:-10]]
    return qargs


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   label_names=(), excluded_sym_names=(),
                   calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   logger=logging):
    """(reference: python/mxnet/contrib/quantization.py quantize_model)

    calib_mode:
      'none'  — dynamic: activation min/max computed in-graph per batch
      'naive' — offline: global min/max over *calib_data* baked in as
                parameters (requires calib_data)
    Returns (qsym, qarg_params, aux_params).
    """
    qsym, calib_points = quantize_symbol(
        sym, excluded_sym_names=excluded_sym_names,
        quantized_dtype=quantized_dtype, calib_mode=calib_mode)
    qargs = _quantize_weights(qsym, arg_params)
    if calib_mode == "naive":
        assert calib_data is not None, \
            "calib_mode='naive' needs calib_data"
        th = _collect_naive_ranges(sym, calib_points, arg_params,
                                   aux_params, calib_data, data_names,
                                   num_calib_examples, label_names)
        for point, (lo, hi) in th.items():
            m = max(abs(lo), abs(hi))  # symmetric (see quantize_symbol)
            logger.info("calibrated %s: [%g, %g] -> +-%g", point, lo,
                        hi, m)
            qargs["%s_min" % point] = nd.array(
                _np.asarray(-m, _np.float32))
            qargs["%s_max" % point] = nd.array(
                _np.asarray(m, _np.float32))
    elif calib_mode != "none":
        raise ValueError("calib_mode must be 'none' or 'naive', got %r"
                         % (calib_mode,))
    return qsym, qargs, dict(aux_params or {})
