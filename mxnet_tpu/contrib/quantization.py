"""INT8 model quantization: graph rewrite + calibration driver.

Reference: ``src/operator/quantization/quantize_graph_pass.cc:119``
(QuantizeGraph inserts quantize/dequantize pairs around ops carrying the
FQuantizedOp attr, ``:92-96``) and the Python driver
``python/mxnet/contrib/quantization.py`` (quantize_model with
calib_mode none/naive).

TPU-native mapping: quantized Convolution/FullyConnected run int8 x int8
-> int32 on the MXU (``ops/quantization.py``); the rewrite inserts
``_contrib_quantize`` on activations (either with calibrated min/max
parameters — calib_mode='naive' — or with in-graph dynamic min/max —
calib_mode='none') and a ``_contrib_dequantize`` on the int32
accumulator; weights are quantized OFFLINE to int8 parameters, so the
serialized quantized model carries int8 weights exactly like the
reference's.
"""

from __future__ import annotations

import logging

import numpy as _np

from .. import ndarray as nd
from .. import symbol as S
from ..symbol.symbol import Node, Symbol

__all__ = ["quantize_symbol", "quantize_model"]

_QUANTIZABLE = ("Convolution", "FullyConnected")


def _entry_symbol(entry):
    return Symbol([entry])


def quantize_symbol(sym, excluded_sym_names=(), quantized_dtype="int8",
                    calib_mode="naive"):
    """Rewrite *sym*, quantizing every Convolution/FullyConnected not in
    *excluded_sym_names*.

    Returns (qsym, calib_points) where calib_points maps
    ``<node name>_data`` -> the ORIGINAL graph entry feeding that node
    (for offline range collection) — empty for calib_mode='none', where
    ranges are computed in-graph per batch (dynamic quantization).
    """
    assert quantized_dtype == "int8", "int8 is the TPU MXU path"
    excluded = set(excluded_sym_names)
    order = sym._topo()
    entry_map = {}       # (id(orig_node), out_idx) -> new entry
    calib_points = {}

    def mapped(entry):
        node, idx = entry
        if node.is_var:
            return (node, idx)
        return entry_map[(id(node), idx)]

    for node in order:
        if node.is_var:
            continue
        new_inputs = [mapped(e) for e in node.inputs]
        if node.op.name in _QUANTIZABLE and node.name not in excluded:
            data = _entry_symbol(new_inputs[0])
            worig = node.inputs[1][0]           # weight var node
            has_bias = not node.params.get("no_bias", False) and \
                len(node.inputs) > 2
            # activation ranges are SYMMETRIC (-M, M): the int32
            # accumulator's real value is then exactly
            # q_d * q_w * (Md/127) * (Mw/127) with no zero-point
            # correction term (the reference's MKLDNN path carries a
            # compensation tensor instead; symmetric is the clean MXU
            # mapping)
            if calib_mode == "none":
                m = S.max(S.abs(data))
                dmin = 0.0 - m
                dmax = m
            else:
                dmin = S.var("%s_data_min" % node.name)
                dmax = S.var("%s_data_max" % node.name)
                calib_points["%s_data" % node.name] = node.inputs[0]
            dq = S._contrib_quantize(data, dmin, dmax, out_type="int8",
                                     name="%s_quantize" % node.name)
            wq = S.var("%s_quantized" % worig.name)
            wmin = S.var("%s_min" % worig.name)
            wmax = S.var("%s_max" % worig.name)
            if node.op.name == "Convolution":
                p = node.params
                q = S._contrib_quantized_conv(
                    dq[0], wq, dq[1], dq[2], wmin, wmax,
                    kernel=p.get("kernel"), stride=p.get("stride"),
                    pad=p.get("pad"), dilate=p.get("dilate"),
                    num_filter=p.get("num_filter"),
                    num_group=p.get("num_group", 1),
                    name="%s_quantized" % node.name)
                out = S._contrib_dequantize(
                    q[0], q[1], q[2], name="%s_dequantize" % node.name)
                if has_bias:
                    bias = _entry_symbol(new_inputs[2])
                    out = S.broadcast_add(
                        out, S.reshape(bias, shape=(1, -1, 1, 1)))
            else:
                p = node.params
                q = S._contrib_quantized_fully_connected(
                    dq[0], wq, dq[1], dq[2], wmin, wmax,
                    num_hidden=p.get("num_hidden"),
                    flatten=p.get("flatten", True),
                    name="%s_quantized" % node.name)
                out = S._contrib_dequantize(
                    q[0], q[1], q[2], name="%s_dequantize" % node.name)
                if has_bias:
                    bias = _entry_symbol(new_inputs[2])
                    out = S.broadcast_add(out,
                                          S.reshape(bias, shape=(1, -1)))
            entry_map[(id(node), 0)] = out._outputs[0]
        else:
            new_node = Node(node.op, node.name, params=node.params,
                            inputs=new_inputs, attrs=node.attrs)
            for i in range(node.num_outputs()):
                entry_map[(id(node), i)] = (new_node, i)

    qsym = Symbol([mapped(e) for e in sym._outputs])
    return qsym, calib_points


class _CalibRunner:
    """Shared calibration-pass driver: binds the collection graph ONCE
    (each bind creates fresh jitted closures — a per-batch or per-pass
    bind would recompile it) and streams every layer output to a
    consume(name, np_array) callback, honoring num_calib_examples."""

    def __init__(self, calib_points, arg_params, aux_params, calib_data,
                 data_names, num_calib_examples, label_names=()):
        self.group = S.Group([_entry_symbol(e)
                              for e in calib_points.values()])
        self.names = list(calib_points)
        self.arg_params = dict(arg_params)
        self.aux_params = dict(aux_params or {})
        self.calib_data = calib_data
        self.data_names = data_names
        self.label_names = label_names
        self.num_calib_examples = num_calib_examples
        self._exe = None

    def run(self, consume):
        self.calib_data.reset()
        seen = 0
        for batch in self.calib_data:
            feeds = {}
            for dn, arr in zip(self.data_names, batch.data):
                feeds[dn] = arr
            if batch.label:
                for ln, arr in zip(self.label_names, batch.label):
                    feeds[ln] = arr
            if self._exe is None:
                self._exe = self.group.bind(
                    args={**self.arg_params, **feeds},
                    aux_states=self.aux_params)
            outs = self._exe.forward(is_train=False, **feeds)
            for n, o in zip(self.names, outs):
                consume(n, o.asnumpy())
            seen += batch.data[0].shape[0]
            if self.num_calib_examples is not None and \
                    seen >= self.num_calib_examples:
                break


def _collect_naive_ranges(sym, calib_points, arg_params, aux_params,
                          calib_data, data_names, num_calib_examples,
                          label_names=()):
    """Global min/max per calibration point over the calib batches
    (reference: quantization.py _LayerOutputMinMaxCollector,
    calib_mode='naive')."""
    runner = _CalibRunner(calib_points, arg_params, aux_params,
                          calib_data, data_names, num_calib_examples,
                          label_names)
    th = {n: (_np.inf, -_np.inf) for n in runner.names}

    def consume(n, v):
        lo, hi = th[n]
        th[n] = (min(lo, float(v.min())), max(hi, float(v.max())))
    runner.run(consume)
    return th


def _kl_optimal_threshold(hist, num_quantized_bins=255):
    """KL-divergence-optimal symmetric clip threshold from a histogram
    of |activation| values (reference: quantization.py
    _get_optimal_threshold, the TensorRT-style entropy calibration).

    Scans candidate clip points; for each, the clipped distribution P
    (outliers folded into the last kept bin) is compared against Q, the
    same mass re-expressed with num_quantized_bins levels.  Returns the
    index (exclusive) of the kept-bin count with minimal KL(P || Q).
    """
    nbins = len(hist)
    hist = hist.astype(_np.float64)
    eps = 1e-6
    best_i, best_kl = nbins, _np.inf
    candidates = list(range(num_quantized_bins, nbins + 1,
                            max(1, num_quantized_bins // 16)))
    if candidates[-1] != nbins:
        candidates.append(nbins)  # the no-clip option must be scorable
    for i in candidates:
        # P: kept range with the clipped-off mass folded into the edge
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        # Q: built from the UNFOLDED histogram, re-binned to
        # num_quantized_bins levels and spread back uniformly over each
        # level's nonzero source bins.  The fold appears only in P —
        # that asymmetry is what charges a clip for the mass it throws
        # away; folding both sides would score "clip everything" as
        # lossless.
        ref = hist[:i]
        q = _np.zeros(i)
        step = i / num_quantized_bins
        for b in range(num_quantized_bins):
            lo = int(b * step)
            hi = max(int((b + 1) * step), lo + 1)
            chunk = ref[lo:hi]
            nz = chunk > 0
            if nz.any():
                q[lo:hi][nz] = chunk.sum() / nz.sum()
        pk = p / p.sum() + eps
        qk = q / max(q.sum(), 1e-12) + eps
        pk /= pk.sum()
        qk /= qk.sum()
        kl = float(_np.sum(pk * _np.log(pk / qk)))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return best_i


def _collect_entropy_ranges(calib_points, arg_params, aux_params,
                            calib_data, data_names, num_calib_examples,
                            label_names=(), nbins=2048):
    """Two passes over the calibration set: (1) global |x| max per
    point, (2) histogram accumulation; then the KL-optimal clip
    (reference: calib_mode='entropy').  The executor is bound once and
    shared by both passes."""
    runner = _CalibRunner(calib_points, arg_params, aux_params,
                          calib_data, data_names, num_calib_examples,
                          label_names)
    names = runner.names
    max_abs = {n: 0.0 for n in names}

    def pass1(n, v):
        a = _np.abs(v)
        max_abs[n] = max(max_abs[n], float(a.max()) if a.size else 0.0)
    runner.run(pass1)

    hists = {n: _np.zeros(nbins, _np.int64) for n in names}

    def pass2(n, v):
        m = max_abs[n] or 1e-8
        # clamp: a non-deterministic calib iterator (reshuffle/augment
        # on reset) can exceed pass-1's max — fold such values into the
        # last bin rather than silently dropping the outlier mass the
        # entropy method exists to measure
        a = _np.minimum(_np.abs(v).ravel(), m)
        h, _ = _np.histogram(a, bins=nbins, range=(0.0, m))
        hists[n] += h
    runner.run(pass2)

    th = {}
    for n in names:
        m = max_abs[n] or 1e-8
        i = _kl_optimal_threshold(hists[n])
        th[n] = (i / len(hists[n])) * m
    return th


def _quantize_weights(sym, arg_params):
    """Offline symmetric int8 weight quantization for every
    '*_quantized' weight var the rewrite introduced."""
    qargs = dict(arg_params)
    still_needed = set(sym.list_arguments())
    for name in still_needed:
        if name.endswith("_quantized") and name[:-10] in arg_params:
            w = arg_params[name[:-10]].asnumpy()
            m = float(_np.abs(w).max()) or 1e-8
            q = _np.clip(_np.round(w * 127.0 / m), -127, 127) \
                .astype(_np.int8)
            qargs[name] = nd.array(q)
            qargs[name[:-10] + "_min"] = nd.array(
                _np.asarray(-m, _np.float32))
            qargs[name[:-10] + "_max"] = nd.array(
                _np.asarray(m, _np.float32))
            if name[:-10] not in still_needed:
                # the fp32 weight may still be consumed by an excluded
                # layer (tied weights) — only drop it when unused
                del qargs[name[:-10]]
    return qargs


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   label_names=(), excluded_sym_names=(),
                   calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   logger=logging):
    """(reference: python/mxnet/contrib/quantization.py quantize_model)

    calib_mode:
      'none'    — dynamic: activation min/max computed in-graph per batch
      'naive'   — offline: global min/max over *calib_data* baked in as
                  parameters (requires calib_data)
      'entropy' — offline: KL-divergence-optimal clip thresholds over
                  *calib_data* (requires calib_data; robust to outlier
                  activations that would stretch naive ranges)
    Returns (qsym, qarg_params, aux_params).
    """
    calib_graph_mode = "none" if calib_mode == "none" else "naive"
    qsym, calib_points = quantize_symbol(
        sym, excluded_sym_names=excluded_sym_names,
        quantized_dtype=quantized_dtype, calib_mode=calib_graph_mode)
    qargs = _quantize_weights(qsym, arg_params)
    if calib_mode in ("naive", "entropy"):
        assert calib_data is not None, \
            "calib_mode=%r needs calib_data" % calib_mode
        if calib_mode == "naive":
            ranges = _collect_naive_ranges(
                sym, calib_points, arg_params, aux_params, calib_data,
                data_names, num_calib_examples, label_names)
            th = {n: max(abs(lo), abs(hi))
                  for n, (lo, hi) in ranges.items()}
        else:
            th = _collect_entropy_ranges(
                calib_points, arg_params, aux_params, calib_data,
                data_names, num_calib_examples, label_names)
        for point, m in th.items():
            logger.info("calibrated %s (%s): +-%g", point, calib_mode, m)
            qargs["%s_min" % point] = nd.array(
                _np.asarray(-m, _np.float32))
            qargs["%s_max" % point] = nd.array(
                _np.asarray(m, _np.float32))
    elif calib_mode != "none":
        raise ValueError("calib_mode must be 'none', 'naive' or "
                         "'entropy', got %r" % (calib_mode,))
    return qsym, qargs, dict(aux_params or {})
