"""Token embeddings (reference: contrib/text/embedding.py).

``CustomEmbedding`` loads any word-vector text file;  ``GloVe`` /
``FastText`` are registered names over the same loader — this image has
no network egress, so pass ``pretrained_file_path`` to a local file
(the reference's auto-download is unavailable and raises a clear error).
"""

from __future__ import annotations

import io
import os

import numpy as _np

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_REGISTRY = {}


def register(embedding_cls):
    """Register an embedding class under its lowercase name
    (reference: embedding.py:40)."""
    _REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding (reference: embedding.py:63)."""
    try:
        cls = _REGISTRY[embedding_name.lower()]
    except KeyError:
        raise KeyError("unknown embedding %r; registered: %s"
                       % (embedding_name, sorted(_REGISTRY)))
    return cls(**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained archive names (reference: embedding.py:90).
    Download is unavailable offline; the names document what the
    reference would fetch."""
    table = {
        "glove": ["glove.6B.50d.txt", "glove.6B.100d.txt",
                  "glove.6B.200d.txt", "glove.6B.300d.txt",
                  "glove.42B.300d.txt", "glove.840B.300d.txt"],
        "fasttext": ["wiki.en.vec", "wiki.simple.vec"],
    }
    if embedding_name is not None:
        return table[embedding_name.lower()]
    return table


class TokenEmbedding(Vocabulary):
    """Vocabulary + vector table (reference: _TokenEmbedding,
    embedding.py:133)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding(self, path, elem_delim, init_unknown_vec):
        if not os.path.isfile(path):
            raise FileNotFoundError(
                "pretrained embedding file %r not found; this build has "
                "no network egress — provide a local file via "
                "pretrained_file_path" % path)
        file_vecs = {}
        with io.open(path, "r", encoding="utf-8", errors="ignore") as f:
            for line in f:
                parts = line.rstrip().split(elem_delim)
                if len(parts) <= 2:
                    continue  # header line (fastText) or malformed
                token, elems = parts[0], parts[1:]
                if self._vec_len == 0:
                    self._vec_len = len(elems)
                elif len(elems) != self._vec_len:
                    continue  # skip malformed rows like the reference
                if token not in file_vecs:
                    file_vecs[token] = _np.asarray(elems,
                                                   dtype=_np.float32)
        # new tokens from the file extend the index; tokens already
        # indexed (vocabulary merge) keep their slot and get their
        # vector filled below
        for t in file_vecs:
            if t not in self._token_to_idx:
                self._token_to_idx[t] = len(self._idx_to_token)
                self._idx_to_token.append(t)
        table = _np.zeros((len(self._idx_to_token), self._vec_len),
                          _np.float32)
        for t, v in file_vecs.items():
            table[self._token_to_idx[t]] = v
        table[0] = init_unknown_vec((self._vec_len,))
        self._idx_to_vec = nd.array(table)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            idx = [self._token_to_idx.get(
                t, self._token_to_idx.get(t.lower(), 0)) for t in toks]
        else:
            idx = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = nd.Embedding(
            nd.array(_np.asarray(idx, _np.float32)), self._idx_to_vec,
            input_dim=self._idx_to_vec.shape[0],
            output_dim=self._vec_len)
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        arr = _np.array(self._idx_to_vec.asnumpy())  # asnumpy views are RO
        newv = new_vectors.asnumpy().reshape(len(toks), self._vec_len)
        for t, v in zip(toks, newv):
            if t not in self._token_to_idx:
                raise ValueError("token %r not in the embedding" % t)
            arr[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(arr)


@register
class CustomEmbedding(TokenEmbedding):
    """Load any ``token<delim>v1<delim>...`` text file
    (reference: embedding.py:659)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 init_unknown_vec=_np.zeros, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        if vocabulary is not None:
            self._merge_vocab(vocabulary)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec)

    def _merge_vocab(self, vocabulary):
        for t in vocabulary.idx_to_token[1:]:
            if t not in self._token_to_idx:
                self._token_to_idx[t] = len(self._idx_to_token)
                self._idx_to_token.append(t)


@register
class GloVe(CustomEmbedding):
    """GloVe vectors (reference: embedding.py:469).  Offline build:
    requires a local ``pretrained_file_path``."""

    def __init__(self, pretrained_file_name="glove.6B.50d.txt",
                 pretrained_file_path=None, **kwargs):
        if pretrained_file_path is None:
            raise FileNotFoundError(
                "GloVe auto-download is unavailable (no network egress); "
                "download %s elsewhere and pass pretrained_file_path"
                % pretrained_file_name)
        super().__init__(pretrained_file_path, elem_delim=" ", **kwargs)


@register
class FastText(CustomEmbedding):
    """fastText vectors (reference: embedding.py:559); offline build —
    see GloVe."""

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 pretrained_file_path=None, **kwargs):
        if pretrained_file_path is None:
            raise FileNotFoundError(
                "FastText auto-download is unavailable (no network "
                "egress); download %s elsewhere and pass "
                "pretrained_file_path" % pretrained_file_name)
        super().__init__(pretrained_file_path, elem_delim=" ", **kwargs)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (reference: embedding.py:720)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__()
        embs = (token_embeddings if isinstance(token_embeddings, list)
                else [token_embeddings])
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        parts = []
        for emb in embs:
            vecs = emb.get_vecs_by_tokens(self._idx_to_token)
            parts.append(vecs.asnumpy())
        table = _np.concatenate(parts, axis=1)
        self._vec_len = table.shape[1]
        self._idx_to_vec = nd.array(table)
