"""Vocabulary (reference: contrib/text/vocab.py:30).

Maps tokens <-> contiguous indices; index 0 is the unknown token, then
reserved tokens, then corpus tokens by frequency (ties by insertion)."""

from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary(object):
    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if unknown_token in reserved_tokens:
                raise ValueError("unknown_token must not be reserved")
            if len(set(reserved_tokens)) != len(reserved_tokens):
                raise ValueError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens else None)
        self._idx_to_token = [unknown_token] + (self._reserved_tokens or [])
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter(counter, most_freq_count, min_freq)

    def _index_counter(self, counter, most_freq_count, min_freq):
        # stable order: by frequency desc, then first-seen
        pairs = sorted(counter.items(), key=lambda kv: -kv[1])
        budget = (most_freq_count if most_freq_count is not None
                  else len(pairs))
        for token, freq in pairs:
            if freq < min_freq or budget <= 0:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                budget -= 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token (or list of tokens) -> index/indices; unknown -> 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range" % i)
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks
