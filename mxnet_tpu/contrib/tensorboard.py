"""TensorBoard bridge (reference: python/mxnet/contrib/tensorboard.py).

``LogMetricsCallback`` forwards eval-metric values to a SummaryWriter.
Any writer object with an ``add_scalar(tag, value, global_step)`` method
works (torch.utils.tensorboard, tensorboardX, or the reference's
dmlc/tensorboard); the dependency stays optional exactly like the
reference's."""

from __future__ import annotations

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback(object):
    """Batch-end callback logging metrics as TensorBoard scalars."""

    def __init__(self, summary_writer=None, logging_dir=None, prefix=None):
        self.prefix = prefix
        if summary_writer is not None:
            self.summary_writer = summary_writer
        else:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError:
                raise ImportError(
                    "LogMetricsCallback needs a SummaryWriter: pass one "
                    "explicitly or install a tensorboard writer package")
            self.summary_writer = SummaryWriter(logging_dir)
        self.step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
