"""Legacy contrib autograd API (reference:
python/mxnet/contrib/autograd.py — the pre-1.0 grad API kept for old
scripts; thin aliases over mxnet_tpu.autograd)."""

from __future__ import annotations

import functools

from .. import autograd as _ag

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient", "grad",
           "grad_and_loss"]


def set_is_training(is_train):
    prev = _ag.is_training()
    _ag.set_training(is_train)
    return prev


train_section = _ag.record
test_section = _ag.pause
mark_variables = _ag.mark_variables
backward = _ag.backward


def compute_gradient(outputs):
    """Deprecated alias: backward on head outputs, returning nothing
    (gradients land in the marked variables)."""
    _ag.backward(outputs)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradients and the loss
    (reference: contrib/autograd.py grad_and_loss)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else argnum
            variables = [args[i] for i in argnums]
        grads = [v.zeros_like() if hasattr(v, "zeros_like") else None
                 for v in variables]
        _ag.mark_variables(variables, grads)
        with _ag.record():
            outputs = func(*args)
        _ag.backward([outputs] if not isinstance(outputs, list)
                     else outputs)
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Return a function computing only gradients."""
    fn = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def wrapped(*args):
        return fn(*args)[0]
    return wrapped
