"""Runtime kernel compilation (reference: python/mxnet/rtc.py —
CudaModule:42 compiles CUDA source via NVRTC, get_kernel:112 extracts an
entry point, launch:185 runs it on NDArrays).

TPU equivalent: the "source" is Python defining JAX/Pallas kernels, and
"compilation" is jit/Mosaic — so ``Module`` exec's kernel source into an
isolated namespace, ``get_kernel`` wraps an entry point as an
NDArray-callable (jit-compiled per signature on first launch), and
``register_op`` promotes a kernel to a full framework operator usable
from nd/sym/gluon like any built-in.  This is the §2.8 RTC hook:
user-supplied kernels compiled at runtime without rebuilding the
framework.
"""

from __future__ import annotations

import jax

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Module", "Kernel", "register_op"]


class Kernel(object):
    """One launchable entry point (reference: rtc.py CudaKernel).

    The wrapped function takes and returns jax arrays; ``launch`` (and
    ``__call__``) move NDArray arguments in and wrap results back.  A
    jitted executable is cached per call signature, like the NVRTC
    kernel cache keyed by compiled PTX in the reference."""

    def __init__(self, fn, name, static_args=()):
        self._fn = fn
        self.name = name
        self._static = tuple(static_args)
        self._jitted = None

    def _compiled(self):
        if self._jitted is None:
            self._jitted = jax.jit(self._fn,
                                   static_argnames=self._static or None)
        return self._jitted

    def __call__(self, *args, **kwargs):
        vals = [a._data if isinstance(a, NDArray) else a for a in args]
        kw = {k: (v._data if isinstance(v, NDArray) else v)
              for k, v in kwargs.items()}
        out = self._compiled()(*vals, **kw)
        if isinstance(out, (list, tuple)):
            return [NDArray(o) for o in out]
        return NDArray(out)

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0, **kwargs):
        """Reference-shaped launch API; grid/block dims are meaningless
        under XLA/Mosaic scheduling and accepted for compatibility."""
        return self(*args, **kwargs)


class Module(object):
    """Compile kernel source at runtime (reference: rtc.py
    CudaModule:42).  *source* is Python text defining functions over jax
    arrays (jnp ops or pallas_call kernels); it executes in an isolated
    namespace with jax/jnp/pallas preloaded, mirroring how the
    reference's source string gets nvrtc-compiled with exports."""

    def __init__(self, source, options=(), exports=()):
        import jax.numpy as jnp
        try:
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu
        except ImportError:  # pallas optional on exotic builds
            pl = pltpu = None
        self._namespace = {"jax": jax, "jnp": jnp, "pl": pl,
                           "pltpu": pltpu}
        try:
            exec(compile(source, "<rtc.Module>", "exec"),
                 self._namespace)
        except Exception as e:
            raise MXNetError("rtc source failed to compile: %s" % e)
        self._exports = set(exports) if exports else None

    def get_kernel(self, name, signature=None, static_args=()):
        """Fetch an entry point (reference: get_kernel:112; the CUDA
        signature string is accepted and ignored — jax infers types)."""
        if self._exports is not None and name not in self._exports:
            raise MXNetError("kernel %r not exported" % name)
        fn = self._namespace.get(name)
        if not callable(fn):
            raise MXNetError("kernel %r not found in rtc source" % name)
        return Kernel(fn, name, static_args)


def register_op(op_name, fn=None, num_outputs=1, input_names=None):
    """Promote a runtime-compiled kernel to a registered operator so it
    works from nd/sym/gluon/executor like a built-in (the deeper TPU
    analogue of launching an RTC kernel inside the engine).  Usable as
    a decorator::

        @mx.rtc.register_op("my_scale")
        def my_scale(x, scale=2.0):
            return x * scale
        ...
        mx.nd.my_scale(a, scale=3.0)
    """
    from .ops import registry as _reg
    from .ndarray import register as _nd_reg
    from .symbol import register as _sym_reg
    from . import ndarray as _nd_pkg
    from . import symbol as _sym_pkg

    def _do(f):
        _reg.register_op(op_name, num_outputs=num_outputs,
                         input_names=input_names)(f)
        op = _reg.get_op(op_name)
        _nd_pkg.__dict__[op_name] = _nd_reg._make_fn(op)
        _sym_pkg.__dict__[op_name] = _sym_reg._make_fn(op)
        return f

    if fn is not None:
        return _do(fn)
    return _do
