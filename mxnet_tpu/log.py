"""Logging helpers (reference: python/mxnet/log.py — get_logger with
the reference's level names and a head-formatted handler)."""

from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING",
           "ERROR", "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

_HEAD_FMT = "%(asctime)-15s %(name)s %(levelname)s %(message)s"


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Configured logger (reference: log.py getLogger): optional file
    sink, timestamped head format, idempotent handler setup."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_configured", False):
        logger.setLevel(level)
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_HEAD_FMT))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxtpu_configured = True
    return logger


getLogger = get_logger  # reference spelling
