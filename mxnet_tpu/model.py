"""Checkpoint format + BatchEndParam (reference: python/mxnet/model.py,
1,012 LoC — save_checkpoint:383 / load_checkpoint:413; the deprecated
FeedForward API is subsumed by mxnet_tpu.module).

Checkpoint format matches the reference's convention:
``prefix-symbol.json`` (graph) + ``prefix-NNNN.params`` (tensors keyed
``arg:<name>`` / ``aux:<name>``) so Module/Gluon/SymbolBlock all share it.
"""

from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym_mod

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save (reference: model.py save_checkpoint:383)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Load (reference: model.py load_checkpoint:413).  Returns
    (symbol, arg_params, aux_params)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params
