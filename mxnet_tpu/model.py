"""Checkpoint format + BatchEndParam (reference: python/mxnet/model.py,
1,012 LoC — save_checkpoint:383 / load_checkpoint:413; the deprecated
FeedForward API is subsumed by mxnet_tpu.module).

Checkpoint format matches the reference's convention:
``prefix-symbol.json`` (graph) + ``prefix-NNNN.params`` (tensors keyed
``arg:<name>`` / ``aux:<name>``) so Module/Gluon/SymbolBlock all share it.
Persistence routes through the resilience subsystem
(mxnet_tpu/resilience/checkpoint.py): every file is written atomically
and committed to a checksum manifest, and loads verify against that
manifest when one exists — a torn or bit-rotted checkpoint fails loudly
at load instead of as a shape error three layers later.
"""

from __future__ import annotations

import logging

from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "fit"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save (reference: model.py save_checkpoint:383) — crash-safe:
    atomic per-file writes plus a checksum-manifest commit (see
    :class:`mxnet_tpu.resilience.CheckpointManager`)."""
    from .resilience.checkpoint import CheckpointManager
    CheckpointManager(prefix).save_checkpoint(
        epoch, symbol=symbol, arg_params=arg_params,
        aux_params=aux_params)


def fit(symbol, train_data, eval_data=None, num_epoch=None, ctx=None,
        eval_metric="acc", optimizer="sgd",
        optimizer_params=(("learning_rate", 0.01),), kvstore="local",
        data_names=("data",), label_names=("softmax_label",),
        logger=None, **kwargs):
    """Legacy one-call training entry (the reference's deprecated
    ``FeedForward.fit`` shape): build a Module over *symbol* and run
    its full ``fit`` loop.  Delegating keeps this entry point
    preemption-safe and job-state-resumable for free — the batch
    boundary honors SIGTERM / ``chaos.preempt_at_batch``, ticks the
    supervisor heartbeat, and accepts the same ``checkpoint_manager``
    / ``resume_from`` / ``checkpoint_every_n_batches`` kwargs as
    ``Module.fit`` (see docs/resilience.md).  Against a ``dist_sync``
    store the loop is also elastic: membership changes re-shard the
    data and rescale the step at batch boundaries, an evicted rank
    re-syncs and rejoins, and a rank retired by ``kv.resize()``
    returns cleanly (docs/resilience.md "Elastic training").  Returns
    the trained Module."""
    from .module import Module
    module = Module(symbol, data_names=data_names,
                    label_names=label_names,
                    logger=logger or logging, context=ctx)
    module.fit(train_data, eval_data=eval_data,
               eval_metric=eval_metric, kvstore=kvstore,
               optimizer=optimizer, optimizer_params=optimizer_params,
               num_epoch=num_epoch, **kwargs)
    return module


def _split_save_dict(save_dict, context="params file"):
    """Split an ``arg:``/``aux:``-keyed save dict into (arg_params,
    aux_params).  Unrecognized key prefixes are warn-and-skipped: a
    corrupt or foreign file announces itself at load time instead of
    surfacing as a shape error three layers later."""
    arg_params = {}
    aux_params = {}
    unknown = []
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            unknown.append(k)
    if unknown:
        logging.getLogger(__name__).warning(
            "%s contains %d key(s) without the expected 'arg:'/'aux:' "
            "prefix (%s%s) — skipped; the file may be foreign (e.g. a "
            "gluon save_parameters file) or corrupt", context,
            len(unknown), ", ".join(repr(k) for k in unknown[:5]),
            ", ..." if len(unknown) > 5 else "")
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load (reference: model.py load_checkpoint:413).  Returns
    (symbol, arg_params, aux_params).  When a resilience manifest
    covers this epoch, the files are checksum-verified first and a
    corrupt/torn checkpoint raises (``CheckpointManager(prefix)
    .restore_latest()`` falls back to the newest intact one)."""
    from .resilience.checkpoint import CheckpointManager
    ok = CheckpointManager(prefix).verify(epoch)
    if ok is False:
        raise MXNetError(
            "checkpoint %r epoch %d failed checksum verification "
            "(torn write or on-disk corruption); use "
            "CheckpointManager(%r).restore_latest() to fall back to "
            "the newest intact checkpoint" % (prefix, epoch, prefix))
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = _split_save_dict(
        save_dict, context="checkpoint %r epoch %d" % (prefix, epoch))
    return symbol, arg_params, aux_params
