"""NDArray save/load (reference: python/mxnet/ndarray/utils.py,
src/ndarray/ndarray.cc:1574 Save / :1691 Load).

Format: a zip archive (numpy ``.npz``) with a magic entry; dict keys are
stored as ``key:<name>``, list items as ``idx:<i>``.  Sparse arrays store
``<name>/data`` + ``<name>/indices`` (+ indptr) with an ``__stype__`` tag.
This is this framework's native checkpoint tensor format (the reference's
raw binary layout is CUDA-era and not reproduced bit-for-bit).
"""

from __future__ import annotations

import numpy as _np

from .ndarray import NDArray, array

_MAGIC = "mxnet_tpu_ndarray_v1"


def _flatten_for_save(data):
    entries = {}
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        items = [("idx:%d" % i, v) for i, v in enumerate(data)]
    elif isinstance(data, dict):
        items = [("key:%s" % k, v) for k, v in data.items()]
    else:
        raise ValueError("save expects NDArray, list or dict")
    for name, v in items:
        if getattr(v, "stype", "default") != "default":
            from . import sparse as _sp
            entries[name + "/__stype__"] = _np.array(v.stype)
            entries[name + "/data"] = v.data.asnumpy()
            entries[name + "/indices"] = v.indices.asnumpy()
            entries[name + "/shape"] = _np.array(v.shape)
            if v.stype == "csr":
                entries[name + "/indptr"] = v.indptr.asnumpy()
        else:
            arr = v.asnumpy()
            if arr.dtype.name not in _NPZ_DTYPES:
                # ml_dtypes extensions (bfloat16) come back from np.load
                # as raw void — store the bytes as uint16 plus a dtype
                # tag so the load path can reinterpret them
                entries[name + "/__dtype__"] = _np.array(arr.dtype.name)
                entries[name + "/bits"] = arr.view(_np.uint16) \
                    if arr.ndim else arr.reshape(1).view(_np.uint16)
                entries[name + "/shape"] = _np.array(arr.shape, _np.int64)
            else:
                entries[name] = arr
    return entries


# dtypes the npz container round-trips natively
_NPZ_DTYPES = {"float16", "float32", "float64", "int8", "int16", "int32",
               "int64", "uint8", "uint16", "uint32", "uint64", "bool"}


def save(fname, data):
    """Save NDArrays to file (reference: mx.nd.save).  Routed through
    the resilience atomic writer (tmp + fsync + rename), so a crash
    mid-save never leaves a torn file at *fname* — streamed, so peak
    memory stays ~one array, not the whole serialized archive."""
    from ..resilience.checkpoint import atomic_write_stream
    entries = _flatten_for_save(data)
    entries["__magic__"] = _np.array(_MAGIC)
    atomic_write_stream(fname, lambda f: _np.savez(f, **entries))


def save_bytes(data):
    """Serialize NDArrays to bytes (reference: MXNDArraySaveRawBytes-style
    in-memory form, used by the C predict ABI)."""
    import io
    entries = _flatten_for_save(data)
    entries["__magic__"] = _np.array(_MAGIC)
    buf = io.BytesIO()
    _np.savez(buf, **entries)
    return buf.getvalue()


def load_bytes(raw):
    """Load NDArrays from bytes produced by :func:`save_bytes` (or the
    contents of a :func:`save` file)."""
    import io
    with _np.load(io.BytesIO(raw), allow_pickle=False) as z:
        return _load_from(z)


def load(fname):
    """Load NDArrays saved by :func:`save`."""
    with _np.load(fname, allow_pickle=False) as z:
        return _load_from(z)


def _load_from(z):
        keys = [k for k in z.files if k != "__magic__"]
        groups = {}
        for k in keys:
            base = k.split("/")[0] if "/" in k else k
            groups.setdefault(base, []).append(k)

        def build(base):
            sub = groups[base]
            if len(sub) == 1 and "/" not in sub[0]:
                return array(z[base])
            if base + "/__dtype__" in sub:
                import ml_dtypes  # noqa: F401 (registers the names)
                dt = _np.dtype(str(z[base + "/__dtype__"]))
                shape = tuple(int(s) for s in z[base + "/shape"])
                return array(z[base + "/bits"].view(dt).reshape(shape),
                             dtype=dt.name)
            from . import sparse as _sp
            stype = str(z[base + "/__stype__"])
            shape = tuple(int(s) for s in z[base + "/shape"])
            if stype == "row_sparse":
                return _sp.row_sparse_array(
                    (z[base + "/data"], z[base + "/indices"]), shape=shape)
            return _sp.csr_matrix(
                (z[base + "/data"], z[base + "/indices"],
                 z[base + "/indptr"]), shape=shape)

        if all(k.split("/")[0].startswith("idx:") for k in groups):
            out = [None] * len(groups)
            for base in groups:
                out[int(base[4:])] = build(base)
            return out
        result = {}
        for base in groups:
            name = base[4:] if base.startswith("key:") else base
            result[name] = build(base)
        return result
