"""Sparse NDArrays: row_sparse and CSR.

Reference: ``python/mxnet/ndarray/sparse.py`` (1,635 LoC) over the stype
machinery in ``include/mxnet/ndarray.h:61-66``.

TPU-native design: XLA has no native sparse tensors, so sparse arrays are
(values, indices[, indptr]) pairs of dense jax arrays — SURVEY.md §7 "hard
part (b)".  row_sparse is the gradient format for embeddings (values row
block + row ids); CSR feeds the LibSVM linear-classification config.  Ops
lower to gather/scatter/segment_sum HLO, which XLA handles well on TPU as
long as nnz shapes are static per compilation.
"""

from __future__ import annotations

import numpy as _np

import jax.numpy as jnp
import jax

from ..base import np_dtype
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "zeros",
           "dot", "retain", "sparse_add", "elemwise_mul"]


class BaseSparseNDArray(NDArray):
    """Common base for sparse stypes; wraps component dense arrays."""

    __slots__ = ("_shape",)

    def __init__(self, data, indices, shape, stype):
        # _data holds the values array; indices et al. go in _aux
        super().__init__(data._data if isinstance(data, NDArray) else data)
        self._aux = [indices._data if isinstance(indices, NDArray)
                     else jnp.asarray(indices)]
        self._shape = tuple(int(s) for s in shape)
        self._stype = stype

    @property
    def shape(self):
        return self._shape

    @property
    def data(self):
        return NDArray(self._data)

    @property
    def indices(self):
        return NDArray(self._aux[0])

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    def asnumpy(self):
        return self.todense().asnumpy()

    def astype(self, dtype, copy=True):
        out = self.copy()
        out._data = out._data.astype(np_dtype(dtype))
        return out

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(map(str, self.shape)),
                                  self.context)

    def tostype(self, stype):
        if stype == self._stype:
            return self
        return cast_storage(self, stype)

    def wait_to_read(self):
        self._data.block_until_ready()
        return self


class RowSparseNDArray(BaseSparseNDArray):
    """values: (nnz_rows, *row_shape); indices: (nnz_rows,) sorted row ids."""

    def __init__(self, data, indices, shape):
        super().__init__(data, indices, shape, "row_sparse")

    def todense(self):
        out = jnp.zeros(self._shape, self._data.dtype)
        idx = self._aux[0].astype(jnp.int32)
        # scatter-ADD, not set: sparse_add may leave duplicate row ids
        # (kvstore reduce concatenates shards) and their values must sum
        return NDArray(out.at[idx].add(self._data))

    def copy(self):
        return RowSparseNDArray(NDArray(self._data), NDArray(self._aux[0]),
                                self._shape)

    def retain(self, rs_indices):
        return retain(self, rs_indices)

    def __add__(self, other):
        return sparse_add(self, other)


class CSRNDArray(BaseSparseNDArray):
    """values/indices: (nnz,); indptr: (rows+1,)."""

    def __init__(self, data, indices, indptr, shape):
        super().__init__(data, indices, shape, "csr")
        self._aux.append(indptr._data if isinstance(indptr, NDArray)
                         else jnp.asarray(indptr))

    @property
    def indptr(self):
        return NDArray(self._aux[1])

    def todense(self):
        rows = self._shape[0]
        indptr = self._aux[1].astype(jnp.int32)
        # row id per nnz via searchsorted over indptr
        nnz = self._data.shape[0]
        pos = jnp.arange(nnz)
        row_ids = jnp.searchsorted(indptr, pos, side="right") - 1
        out = jnp.zeros(self._shape, self._data.dtype)
        cols = self._aux[0].astype(jnp.int32)
        return NDArray(out.at[row_ids, cols].set(self._data))

    def copy(self):
        return CSRNDArray(NDArray(self._data), NDArray(self._aux[0]),
                          NDArray(self._aux[1]), self._shape)

    def __getitem__(self, key):
        if isinstance(key, slice):
            start = key.start or 0
            stop = key.stop if key.stop is not None else self._shape[0]
            indptr = _np.asarray(self._aux[1])
            lo, hi = int(indptr[start]), int(indptr[stop])
            new_indptr = indptr[start:stop + 1] - indptr[start]
            return CSRNDArray(
                NDArray(self._data[lo:hi]), NDArray(self._aux[0][lo:hi]),
                NDArray(jnp.asarray(new_indptr)),
                (stop - start,) + self._shape[1:])
        raise TypeError("CSRNDArray indexing supports row slices only")


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(data, np_dtype(dtype) if dtype else None)
        indices = jnp.asarray(indices, jnp.int32)
        return RowSparseNDArray(NDArray(data), NDArray(indices), shape)
    # dense input -> compress (host-side)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1)
    if dtype:
        dense = dense.astype(np_dtype(dtype))
    nz_rows = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0,
                                axis=1))[0]
    return RowSparseNDArray(NDArray(jnp.asarray(dense[nz_rows])),
                            NDArray(jnp.asarray(nz_rows, dtype=jnp.int32)),
                            shape or dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = jnp.asarray(data, np_dtype(dtype) if dtype else None)
        return CSRNDArray(NDArray(data),
                          NDArray(jnp.asarray(indices, jnp.int32)),
                          NDArray(jnp.asarray(indptr, jnp.int32)), shape)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1)
    if dtype:
        dense = dense.astype(np_dtype(dtype))
    rows, cols = _np.nonzero(dense)
    data = dense[rows, cols]
    indptr = _np.zeros(dense.shape[0] + 1, _np.int32)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr)
    return CSRNDArray(NDArray(jnp.asarray(data)),
                      NDArray(jnp.asarray(cols, dtype=jnp.int32)),
                      NDArray(jnp.asarray(indptr)), shape or dense.shape)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = np_dtype(dtype)
    if stype == "row_sparse":
        row_shape = shape[1:]
        return RowSparseNDArray(NDArray(jnp.zeros((0,) + row_shape, dt)),
                                NDArray(jnp.zeros((0,), jnp.int32)), shape)
    if stype == "csr":
        return CSRNDArray(NDArray(jnp.zeros((0,), dt)),
                          NDArray(jnp.zeros((0,), jnp.int32)),
                          NDArray(jnp.zeros((shape[0] + 1,), jnp.int32)),
                          shape)
    if stype == "default":
        from . import ndarray as _nd
        return _nd.zeros(shape, ctx, dtype)
    raise ValueError(stype)


def cast_storage(arr, stype):
    """dense<->sparse conversion (reference: cast_storage op,
    src/operator/tensor/cast_storage-inl.h)."""
    if arr.stype == stype:
        return arr
    if stype == "default":
        return arr.todense()
    if isinstance(arr, BaseSparseNDArray):
        arr = arr.todense()
    if stype == "row_sparse":
        return row_sparse_array(arr, shape=arr.shape)
    if stype == "csr":
        return csr_matrix(arr, shape=arr.shape)
    raise ValueError(stype)


# ---------------------------------------------------------------------------
# sparse ops
# ---------------------------------------------------------------------------


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr × dense / dense × rsp dot (reference: src/operator/tensor/dot-inl.h
    sparse paths)."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and \
            not isinstance(rhs, BaseSparseNDArray):
        # one lowering shared with the graph-level dot op
        from ..ops.sparse_graph import CsrCarrier, csr_dot_dense
        carrier = CsrCarrier(lhs._data, lhs._aux[0], lhs._aux[1],
                             lhs.shape)
        r = rhs._data
        if transpose_b:
            r = jnp.swapaxes(r, -1, -2) if r.ndim > 1 else r
        return NDArray(csr_dot_dense(carrier, r, transpose_a))
    if not isinstance(lhs, BaseSparseNDArray) and \
            isinstance(rhs, BaseSparseNDArray):
        return NDArray(jnp.dot(lhs._data, rhs.todense()._data))
    return NDArray(jnp.dot(lhs.todense()._data if isinstance(
        lhs, BaseSparseNDArray) else lhs._data,
        rhs.todense()._data if isinstance(rhs, BaseSparseNDArray)
        else rhs._data))


def retain(rsp, indices):
    """Keep only the requested rows (reference: sparse_retain op)."""
    want = indices._data.astype(jnp.int32) if isinstance(indices, NDArray) \
        else jnp.asarray(indices, jnp.int32)
    have = rsp._aux[0]
    # position of each wanted row in `have` (or -1)
    pos = jnp.searchsorted(have, want)
    pos = jnp.clip(pos, 0, max(have.shape[0] - 1, 0))
    ok = (have.shape[0] > 0) & (have[pos] == want) if have.shape[0] else \
        jnp.zeros(want.shape, bool)
    vals = jnp.where(ok.reshape((-1,) + (1,) * (rsp._data.ndim - 1)),
                     rsp._data[pos] if have.shape[0] else
                     jnp.zeros((want.shape[0],) + rsp._data.shape[1:],
                               rsp._data.dtype),
                     0)
    return RowSparseNDArray(NDArray(vals), NDArray(want), rsp.shape)


def sparse_add(a, b):
    if isinstance(a, RowSparseNDArray) and isinstance(b, RowSparseNDArray):
        idx = jnp.concatenate([a._aux[0], b._aux[0]])
        vals = jnp.concatenate([a._data, b._data])
        order = jnp.argsort(idx)
        return RowSparseNDArray(NDArray(vals[order]), NDArray(idx[order]),
                                a.shape)  # may contain dup rows; dense on use
    return NDArray(a.todense()._data + (b.todense()._data if isinstance(
        b, BaseSparseNDArray) else b._data))


def elemwise_mul(a, b):
    return NDArray(a.todense()._data * (b.todense()._data if isinstance(
        b, BaseSparseNDArray) else b._data))


def compress_rowsparse(dense_grad, rtol=0.0):
    """Dense gradient -> RowSparseNDArray keeping only rows with any
    nonzero entry.  The TPU-native sparse-gradient stance: gradients are
    COMPUTED dense (XLA scatter-add on the MXU/VPU is the fast path);
    sparsity is recovered at the framework boundary where it pays —
    kvstore wire transfer and lazy row-wise optimizer updates
    (reference: sparse_grad=True Embedding gradients,
    src/operator/tensor/indexing_op.cc EmbeddingOpBackwardEx)."""
    import numpy as __np
    d = dense_grad._data if isinstance(dense_grad, NDArray) else \
        jnp.asarray(dense_grad)
    flat = __np.asarray(jnp.abs(d).max(
        axis=tuple(range(1, d.ndim)))) if d.ndim > 1 else __np.abs(
        __np.asarray(d))
    rows = __np.where(flat > rtol)[0].astype(__np.int32)
    return RowSparseNDArray(NDArray(d[jnp.asarray(rows)]),
                            NDArray(jnp.asarray(rows)),
                            tuple(int(s) for s in d.shape))


def _prep_row_grad(weight, rsp_grad, rescale_grad, clip_gradient, wd):
    """Shared row-update preamble: gather touched rows, rescale/clip the
    sparse gradient, add weight decay on those rows only (the reference's
    lazy_update semantics: untouched rows see no wd either)."""
    rows = rsp_grad._aux[0].astype(jnp.int32)
    g = rsp_grad._data * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd:
        g = g + wd * weight._data[rows]
    return rows, g


def sgd_row_update(weight, rsp_grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """Lazy row-wise SGD: touches only the gradient's rows (reference:
    sgd_update row_sparse path, optimizer_op.cc lazy_update)."""
    rows, g = _prep_row_grad(weight, rsp_grad, rescale_grad,
                             clip_gradient, wd)
    weight._data = weight._data.at[rows].add(
        (-lr * g).astype(weight._data.dtype))
    return weight


def sgd_mom_row_update(weight, rsp_grad, mom, lr, momentum=0.9, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """Lazy momentum SGD: momentum decays only on touched rows
    (reference: sgd_mom_update row_sparse semantics)."""
    rows, g = _prep_row_grad(weight, rsp_grad, rescale_grad,
                             clip_gradient, wd)
    m_rows = momentum * mom._data[rows] - lr * g
    mom._data = mom._data.at[rows].set(m_rows.astype(mom._data.dtype))
    weight._data = weight._data.at[rows].add(
        m_rows.astype(weight._data.dtype))
    return weight, mom


def adagrad_row_update(weight, rsp_grad, history, lr, epsilon=1e-7,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Lazy row-wise AdaGrad (reference: _sparse_adagrad_update,
    optimizer_op.cc AdagradUpdateEx row_sparse path)."""
    rows, g = _prep_row_grad(weight, rsp_grad, rescale_grad,
                             clip_gradient, wd)
    h_rows = history._data[rows] + jnp.square(g)
    history._data = history._data.at[rows].set(
        h_rows.astype(history._data.dtype))
    weight._data = weight._data.at[rows].add(
        (-lr * g / (jnp.sqrt(h_rows) + epsilon)).astype(
            weight._data.dtype))
    return weight, history
