"""NDArray — the imperative tensor.

Reference: ``python/mxnet/ndarray/ndarray.py`` (class :170) over the C++
``NDArray`` (``src/ndarray/ndarray.cc``, ``include/mxnet/ndarray.h``).

TPU-native design: an NDArray owns a ``jax.Array``.  JAX dispatch is already
async (the reference needed the threaded engine for this; PJRT gives it to
us), so ops return immediately and ``asnumpy()`` is the sync point exactly
like the reference's ``WaitToRead``.  Mutation (``a += b``, ``a[:] = x``,
optimizer updates) rebinds the handle to a fresh functional value — with
buffer donation under jit this reuses the same HBM, reproducing the in-place
semantics without an engine var-graph.
"""

from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import np_dtype, dtype_name
from ..context import Context, current_context
from ..ops import registry as _reg
from .. import autograd as _ag
from .. import sanitizer as _sanitizer
from ..observability import metrics as _metrics

# module-level instrument refs: asnumpy is the framework's d2h choke
# point (asscalar/item/tolist/__float__ route through it), so the
# counters it bumps must not pay a registry lookup per call
_HOST_TRANSFERS = _metrics.counter(
    "host_transfers_total",
    "device->host syncs through the asnumpy choke point")
_HOST_TRANSFER_BYTES = _metrics.counter(
    "host_transfer_bytes_total",
    "bytes moved device->host through asnumpy")
_DEVICE_PUT_ELIDED = _metrics.counter(
    "device_put_elided_total",
    "host->device transfers skipped because the array was already "
    "committed to its target device/sharding (device-resident input)")

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "zeros_like", "ones_like", "concatenate", "imperative_invoke",
           "waitall", "moveaxis"]


def _ctx_of(jarr):
    try:
        dev = list(jarr.devices())[0]
    except (AttributeError, TypeError, IndexError, RuntimeError,
            ValueError):
        # tracers raise ConcretizationTypeError (a TypeError) on
        # .devices(); abstract values lack the attribute; deleted
        # (donated) buffers raise RuntimeError.  Anything else — e.g.
        # a real jax dispatch failure — must propagate, not default to
        # current_context()
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


class NDArray:
    """Multi-dimensional array on a device, with async semantics."""

    __slots__ = ("_data", "_tape_entry", "_grad", "_stype", "_aux")

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        if ctx is not None:
            data = jax.device_put(data, Context(ctx).jax_device)
        self._data = data
        self._tape_entry = None
        self._grad = None
        self._stype = "default"
        self._aux = None

    # -- properties -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return _ctx_of(self._data)

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        # the reference exposes a ctypes handle; ours is the jax.Array
        return self._data

    @property
    def T(self):
        return transpose(self)

    # -- sync / conversion ------------------------------------------------
    def asnumpy(self):
        """Copy to a numpy array, blocking until the value is ready
        (reference: WaitToRead + SyncCopyToCPU, ndarray.py asnumpy).

        This is the framework's device->host choke point (asscalar/
        item/tolist/__float__ all route here), so the graftsan
        transfer guard hooks it: inside a guarded hot-path region the
        sync raises at this touch site.  asnumpy is already a blocking
        sync — the check is one env read, invisible next to the copy."""
        if _sanitizer._transfer_active():
            _sanitizer.transfer_check("asnumpy()", self._data.shape)
        # same choke point feeds the always-on transfer telemetry:
        # count + bytes (shape metadata only — no extra sync)
        _HOST_TRANSFERS.inc()
        _HOST_TRANSFER_BYTES.inc(int(getattr(self._data, "nbytes", 0)))
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        self._data.block_until_ready()
        return self

    def astype(self, dtype, copy=True):
        dt = np_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return _invoke("Cast", [self], {"dtype": dtype_name(dt)})

    def copy(self):
        return _invoke("_copy", [self], {})

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._data = jax.device_put(
                self._data.astype(other._data.dtype)
                if self._data.dtype != other._data.dtype else self._data,
                list(other._data.devices())[0])
            other._tape_entry = None
            return other
        if isinstance(other, Context):
            return NDArray(self._data, ctx=other)
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, ctx):
        ctx = Context(ctx)
        if ctx == self.context:
            return self
        return NDArray(self._data, ctx=ctx)

    as_in_ctx = as_in_context

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    def asnpy(self):
        return self.asnumpy()

    def tolist(self):
        return self.asnumpy().tolist()

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer and mark this array as a variable
        (reference: ndarray.py attach_grad -> MarkVariables)."""
        grad = zeros_like(self)
        _ag.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        out = NDArray(self._data)
        return out

    # -- python protocol ---------------------------------------------------
    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(map(str, self.shape)), self.context)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asnumpy())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # arithmetic
    def __add__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _invoke("_rminus_scalar", [self], {"scalar": float(other)})

    def __mul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _invoke("_rdiv_scalar", [self], {"scalar": float(other)})

    def __mod__(self, other):
        return _binary("broadcast_mod", "_mod_scalar", self, other)

    def __rmod__(self, other):
        return _invoke("_rmod_scalar", [self], {"scalar": float(other)})

    def __pow__(self, other):
        return _binary("broadcast_power", "_power_scalar", self, other)

    def __rpow__(self, other):
        return _invoke("_rpower_scalar", [self], {"scalar": float(other)})

    def __neg__(self):
        return _invoke("negative", [self], {})

    def __abs__(self):
        return _invoke("abs", [self], {})

    def __eq__(self, other):
        return _binary("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        return _binary("broadcast_not_equal", "_not_equal_scalar", self,
                       other)

    def __gt__(self, other):
        return _binary("broadcast_greater", "_greater_scalar", self, other)

    def __ge__(self, other):
        return _binary("broadcast_greater_equal", "_greater_equal_scalar",
                       self, other)

    def __lt__(self, other):
        return _binary("broadcast_lesser", "_lesser_scalar", self, other)

    def __le__(self, other):
        return _binary("broadcast_lesser_equal", "_lesser_equal_scalar",
                       self, other)

    __hash__ = object.__hash__

    # in-place (rebind; donation under jit reuses the buffer)
    def __iadd__(self, other):
        out = self.__add__(other)
        self._data, self._tape_entry = out._data, out._tape_entry
        return self

    def __isub__(self, other):
        out = self.__sub__(other)
        self._data, self._tape_entry = out._data, out._tape_entry
        return self

    def __imul__(self, other):
        out = self.__mul__(other)
        self._data, self._tape_entry = out._data, out._tape_entry
        return self

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._data, self._tape_entry = out._data, out._tape_entry
        return self

    # indexing
    def __getitem__(self, key):
        key = _clean_key(key)
        out = NDArray(self._data[key])
        if _ag.is_recording() and self._tape_entry is not None:
            def fn(x):
                return x[key]
            _record_simple(fn, [self], [out])
        return out

    def __setitem__(self, key, value):
        key = _clean_key(key)
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, (int, float)):
            value = jnp.asarray(value, self._data.dtype)
        else:
            value = jnp.asarray(value, self._data.dtype)
        self._data = self._data.at[key].set(value.astype(self._data.dtype))
        self._tape_entry = None

    # -- op methods (mirror of reference NDArray methods) ------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _invoke("Reshape", [self],
                       {"shape": tuple(shape),
                        "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return _invoke("reshape_like", [self, other], {})

    def expand_dims(self, axis):
        return _invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _invoke("squeeze", [self], {"axis": axis})

    def flatten(self):
        return _invoke("Flatten", [self], {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke("transpose", [self], {"axes": axes or None})

    def swapaxes(self, dim1, dim2):
        return _invoke("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def broadcast_to(self, shape):
        return _invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return _invoke("broadcast_like", [self, other], {})

    def slice(self, begin, end, step=None):
        return _invoke("slice", [self],
                       {"begin": tuple(begin), "end": tuple(end),
                        "step": tuple(step) if step else ()})

    def slice_axis(self, axis, begin, end):
        return _invoke("slice_axis", [self],
                       {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return _invoke("take", [self, _as_nd(indices)],
                       {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return _invoke("one_hot", [self], dict(depth=depth, **kw))

    def pick(self, index, axis=-1, keepdims=False):
        idx = _as_nd(index)
        data = jnp.take_along_axis(
            self._data,
            jnp.expand_dims(idx._data.astype(jnp.int32), axis), axis)
        out = NDArray(data if keepdims else jnp.squeeze(data, axis))
        if _ag.is_recording() and self._tape_entry is not None:
            iarr = idx._data

            def fn(x):
                d = jnp.take_along_axis(
                    x, jnp.expand_dims(iarr.astype(jnp.int32), axis), axis)
                return d if keepdims else jnp.squeeze(d, axis)
            _record_simple(fn, [self], [out])
        return out

    def clip(self, a_min=None, a_max=None):
        return _invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return _invoke("abs", [self], {})

    def sign(self):
        return _invoke("sign", [self], {})

    def sqrt(self):
        return _invoke("sqrt", [self], {})

    def square(self):
        return _invoke("square", [self], {})

    def exp(self):
        return _invoke("exp", [self], {})

    def log(self):
        return _invoke("log", [self], {})

    def relu(self):
        return _invoke("relu", [self], {})

    def sigmoid(self):
        return _invoke("sigmoid", [self], {})

    def tanh(self):
        return _invoke("tanh", [self], {})

    def softmax(self, axis=-1):
        return _invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return _invoke("log_softmax", [self], {"axis": axis})

    def sum(self, axis=None, keepdims=False, **kw):
        return _invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return _invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return _invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return _invoke("norm", [self],
                       {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return _invoke("argsort", [self],
                       {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return _invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _invoke("topk", [self], {"axis": axis, "k": k,
                                        "ret_typ": ret_typ,
                                        "is_ascend": is_ascend})

    def flip(self, axis):
        return _invoke("reverse", [self], {"axis": axis})

    def tile(self, reps):
        return _invoke("tile", [self], {"reps": tuple(reps)})

    def repeat(self, repeats, axis=None):
        return _invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke("SliceChannel", [self],
                       {"num_outputs": num_outputs, "axis": axis,
                        "squeeze_axis": squeeze_axis})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _invoke("dot", [self, other],
                       {"transpose_a": transpose_a,
                        "transpose_b": transpose_b})

    def zeros_like(self):
        return zeros_like(self)

    def ones_like(self):
        return ones_like(self)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _clean_key(key):
    if isinstance(key, NDArray):
        return key._data.astype(jnp.int32)
    if isinstance(key, tuple):
        return tuple(_clean_key(k) if isinstance(k, NDArray) else k
                     for k in key)
    return key


def _as_nd(x, dtype=None):
    if isinstance(x, NDArray):
        return x
    return NDArray(jnp.asarray(x, np_dtype(dtype) if dtype else None))


def _binary(op_name, scalar_op, lhs, rhs):
    if isinstance(rhs, NDArray):
        return _invoke(op_name, [lhs, rhs], {})
    if isinstance(rhs, (int, float, _np.generic)):
        return _invoke(scalar_op, [lhs], {"scalar": float(rhs)})
    return _invoke(op_name, [lhs, _as_nd(rhs)], {})


def _record_simple(fn, nd_inputs, nd_outputs):
    _ag.record_op(fn, nd_inputs, nd_outputs)


def _invoke(op_name, nd_inputs, params, out=None):
    """The eager dispatch path (reference stack 3.1: MXImperativeInvokeEx ->
    Imperative::Invoke -> engine push; here: executable-cache call)."""
    op = _reg.get_op(op_name)
    arrays = [x._data for x in nd_inputs]
    rng = None
    if op.needs_rng:
        from ..runtime import rng as _rngmod
        rng = _rngmod.next_key()
        extra = {k: v for k, v in params.items() if k != "training"}
        if "training" in _op_param_names(op):
            extra["training"] = _ag.is_training() or params.get(
                "training", False)
        params = extra
    elif "training" in _op_param_names(op):
        params = dict(params)
        params.setdefault("training", _ag.is_training())
    raw_out = _reg.invoke(op, arrays, params, rng=rng)
    outputs = [NDArray(o) for o in raw_out]
    if _ag.is_recording():
        _static, dyn, frozen = _reg.split_params(op, params)
        _ag.record_op(None, nd_inputs, outputs, rng=rng,
                      op_ref=(op.name, frozen, tuple(sorted(dyn))),
                      dyn=dyn)
    from ..runtime import engine as _eng
    if _eng.is_naive():
        for o in outputs:
            o._data.block_until_ready()
    visible = outputs[:op.n_visible(params)]
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, visible):
            dst._data = src._data
            dst._tape_entry = src._tape_entry
        return out
    if len(visible) == 1:
        return visible[0]
    return visible


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _op_param_names(op):
    import inspect
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return ()
    return tuple(p.name for p in sig.parameters.values()
                 if p.default is not inspect.Parameter.empty)


def imperative_invoke(op_name, *nd_inputs, out=None, **params):
    """Generic imperative invoke used by the generated op functions."""
    return _invoke(op_name, list(nd_inputs), params, out=out)


# ---------------------------------------------------------------------------
# creation functions
# ---------------------------------------------------------------------------


def _already_placed(arr, dev):
    """Is *arr* a live jax array COMMITTED to exactly *dev*?  Only a
    committed array may skip ``device_put``: committedness is part of
    the jit cache key (the graftsan recompile lesson — see
    Module._setup_fused), so eliding for an uncommitted array would
    flip it between steps and silently recompile the fused program."""
    if not isinstance(arr, jax.Array) or \
            not getattr(arr, "_committed", False):
        return False
    try:
        return arr.devices() == {dev}
    except RuntimeError as e:
        # a donated/deleted buffer: let device_put raise the real
        # use-after-donate error at the transfer site
        import logging
        logging.getLogger(__name__).debug(
            "_already_placed probe failed (%s); routing through "
            "device_put", e)
        return False


def _place(arr, ctx):
    ctx = Context(ctx) if ctx is not None else current_context()
    dev = ctx.jax_device
    if _already_placed(arr, dev):
        # device-resident input (e.g. a DevicePrefetcher ring batch):
        # the put would be a committed->same-device no-op — skip it
        # and count the skip (docs/perf_input_pipeline.md)
        _DEVICE_PUT_ELIDED.inc()
        return arr
    return jax.device_put(arr, dev)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        if getattr(source_array, "_aux", None) is not None:
            # sparse: _data is values-only; array() densifies
            source_array = source_array.asnumpy()
        else:
            # dense: share the (immutable) device buffer instead of a
            # device->host->device round-trip; mutation rebinds
            # handles, so copy semantics are preserved
            arr = source_array._data
            if dtype is None and arr.dtype == _np.float64:
                dtype = "float32"  # reference float-array default
            if dtype is not None and arr.dtype != np_dtype(dtype):
                arr = arr.astype(np_dtype(dtype))
            return NDArray(_place(arr, ctx))
    np_arr = _np.asarray(source_array)
    if dtype is None and np_arr.dtype == _np.float64:
        dtype = "float32"  # reference defaults float arrays to float32
    arr = jnp.asarray(np_arr, np_dtype(dtype) if dtype else None)
    return NDArray(_place(arr, ctx))


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_place(jnp.zeros(shape, np_dtype(dtype)), ctx))


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_place(jnp.ones(shape, np_dtype(dtype)), ctx))


def full(shape, val, ctx=None, dtype=None):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_place(jnp.full(shape, val, np_dtype(dtype)), ctx))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    out = jnp.arange(start, stop, step, np_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return NDArray(_place(out, ctx))


def zeros_like(other):
    return NDArray(jnp.zeros_like(other._data))


def ones_like(other):
    return NDArray(jnp.ones_like(other._data))


def concatenate(arrays, axis=0, always_copy=True):
    return _invoke("Concat", list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    return NDArray(jnp.moveaxis(tensor._data, source, destination))


def transpose(data, axes=None):
    return _invoke("transpose", [data], {"axes": axes})


def waitall():
    from ..runtime import engine
    engine.wait_all()
