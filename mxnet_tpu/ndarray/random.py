"""nd.random namespace (reference: python/mxnet/ndarray/random.py)."""

from __future__ import annotations

from .ndarray import imperative_invoke, NDArray
from ..base import dtype_name


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def _maybe_sample(op_scalar, op_sample, arrs, shape, dtype, out=None,
                  **scalars):
    nd_args = [a for a in arrs if isinstance(a, NDArray)]
    if nd_args:
        return imperative_invoke(op_sample, *nd_args, shape=_shape(shape),
                                 dtype=dtype, out=out)
    return imperative_invoke(op_scalar, shape=_shape(shape), dtype=dtype,
                             out=out, **scalars)


def uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, out=None,
            **kwargs):
    if out is not None and not shape:
        shape = out.shape
    return _maybe_sample("_random_uniform", "_sample_uniform", (low, high),
                         shape, dtype, out=out,
                         low=float(low) if not isinstance(low, NDArray)
                         else low,
                         high=float(high) if not isinstance(high, NDArray)
                         else high)


def normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, out=None,
           **kwargs):
    if out is not None and not shape:
        shape = out.shape
    return _maybe_sample("_random_normal", "_sample_normal", (loc, scale),
                         shape, dtype, out=out, loc=loc, scale=scale)


randn = normal


def gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None,
          out=None):
    return _maybe_sample("_random_gamma", "_sample_gamma", (alpha, beta),
                         shape, dtype, alpha=alpha, beta=beta)


def exponential(scale=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return imperative_invoke("_random_exponential", lam=1.0 / scale,
                             shape=_shape(shape), dtype=dtype)


def poisson(lam=1.0, shape=(), dtype="float32", ctx=None, out=None):
    return imperative_invoke("_random_poisson", lam=lam,
                             shape=_shape(shape), dtype=dtype)


def negative_binomial(k=1, p=1.0, shape=(), dtype="float32", ctx=None,
                      out=None):
    return imperative_invoke("_random_negative_binomial", k=k, p=p,
                             shape=_shape(shape), dtype=dtype)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(),
                                  dtype="float32", ctx=None, out=None):
    return imperative_invoke("_random_generalized_negative_binomial",
                             mu=mu, alpha=alpha, shape=_shape(shape),
                             dtype=dtype)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None):
    return imperative_invoke("_random_randint", low=low, high=high,
                             shape=_shape(shape), dtype=dtype)


def multinomial(data, shape=(), get_prob=False, dtype="int32", out=None):
    return imperative_invoke("_sample_multinomial", data,
                             shape=_shape(shape), get_prob=get_prob,
                             dtype=dtype)


def shuffle(data, out=None):
    return imperative_invoke("shuffle", data)


def bernoulli(p=0.5, shape=(), dtype="float32", ctx=None, out=None):
    return imperative_invoke("_random_bernoulli", p=p, shape=_shape(shape),
                             dtype=dtype)
