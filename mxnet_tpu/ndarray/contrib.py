"""Imperative control flow (reference: python/mxnet/ndarray/contrib.py
foreach:134, while_loop:230, cond:398).

Like the reference, the imperative versions are plain Python loops —
every op inside is taped, so autograd works; data-dependent trip counts
are allowed because nothing is being compiled.  For the compiled
(`lax.scan`) path use the symbolic API or hybridize.
"""

from __future__ import annotations

from .ndarray import NDArray


def _stack(*arrs, axis=0):
    import mxnet_tpu.ndarray as nd_pkg
    return nd_pkg.stack(*arrs, axis=axis)

__all__ = ["foreach", "while_loop", "cond", "rand_zipfian"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def foreach(body, data, init_states):
    """Loop body(data_t, states) -> (outputs, new_states) over axis 0."""
    data_l = _as_list(data)
    states = init_states
    T = data_l[0].shape[0]
    data_scalar = not isinstance(data, (list, tuple))
    outputs = None
    outs_scalar = True
    for t in range(T):
        slices = [d[t] for d in data_l]
        outs, states = body(slices[0] if data_scalar else slices, states)
        outs_scalar = not isinstance(outs, (list, tuple))
        outs_l = _as_list(outs)
        if outputs is None:
            outputs = [[] for _ in outs_l]
        for acc, o in zip(outputs, outs_l):
            acc.append(o)
    stacked = [_stack(*acc, axis=0) for acc in (outputs or [])]
    result = stacked[0] if outs_scalar and len(stacked) == 1 else stacked
    return result, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run func while cond holds (dynamic trip count, eager only).
    Returns (stacked outputs of executed steps, final loop_vars)."""
    lvars = _as_list(loop_vars)
    lscalar = not isinstance(loop_vars, (list, tuple))
    outputs = None
    steps = 0
    while bool(cond(*lvars).asnumpy().reshape(())):
        if max_iterations is not None and steps >= max_iterations:
            break
        outs, new_vars = func(*lvars)
        lvars = _as_list(new_vars)
        outs_l = _as_list(outs)
        if outputs is None:
            outputs = [[] for _ in outs_l]
        for acc, o in zip(outputs, outs_l):
            acc.append(o)
        steps += 1
    stacked = [_stack(*acc, axis=0) for acc in (outputs or [])]
    result = stacked[0] if len(stacked) == 1 else stacked
    return result, (lvars[0] if lscalar and len(lvars) == 1 else lvars)


def cond(pred, then_func, else_func):
    """Eager branch on a scalar NDArray predicate."""
    if bool(pred.asnumpy().reshape(())):
        return then_func()
    return else_func()


def rand_zipfian(true_classes, num_sampled, range_max):
    """Log-uniform (Zipfian) candidate sampler (reference:
    python/mxnet/ndarray/contrib.py rand_zipfian): draw num_sampled
    candidates WITH replacement from
    P(c) = (log(c+2) - log(c+1)) / log(range_max + 1) and return
    (samples int64, expected_count_true, expected_count_sampled) where
    expected_count = P(c) * num_sampled — the sampled-softmax/NCE logit
    correction term.
    """
    import numpy as _np
    import mxnet_tpu.ndarray as nd_pkg

    log_range = _np.log(range_max + 1)
    u = nd_pkg.random.uniform(0, 1, (int(num_sampled),)).asnumpy()
    sampled = (_np.exp(u.astype(_np.float64) * log_range) - 1)         .astype(_np.int64) % range_max
    sampled_nd = nd_pkg.array(sampled)   # int64 ids, like the reference

    def expected(cls):
        cls = _np.asarray(cls, _np.float64)
        p = _np.log((cls + 2.0) / (cls + 1.0)) / log_range
        return p * num_sampled

    exp_true = nd_pkg.array(expected(
        true_classes.asnumpy()).astype(_np.float32))
    exp_sampled = nd_pkg.array(expected(sampled).astype(_np.float32))
    return sampled_nd, exp_true, exp_sampled
