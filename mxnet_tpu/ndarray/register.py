"""Generate the ``nd.*`` op functions from the registry.

Reference: ``python/mxnet/ndarray/register.py:30``
(_generate_ndarray_function_code) — the reference generates Python source
per C-registered op at import; we close over the in-process registry
instead.  Inputs may be passed positionally or by their declared names
(e.g. ``nd.FullyConnected(data=x, weight=w, bias=b, num_hidden=10)``).
"""

from __future__ import annotations

from ..ops import registry as _reg
from .ndarray import NDArray, imperative_invoke


def _make_fn(op):
    def fn(*args, out=None, name=None, **kwargs):
        inputs = []
        pos_params = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            else:
                pos_params.append(a)
        params = {}
        named = {}
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                named[k] = v
            else:
                params[k] = v
        if pos_params:
            # positional scalars map onto the op's params in order
            # (e.g. nd.one_hot(indices, depth))
            free = [p for p in op.param_names if p not in params]
            if len(pos_params) > len(free):
                raise TypeError("%s: too many positional arguments"
                                % op.name)
            for p, v in zip(free, pos_params):
                params[p] = v
        if named:
            for nm in op.input_names[len(inputs):]:
                if nm in named:
                    inputs.append(named.pop(nm))
            if named:
                raise TypeError("%s got unexpected NDArray kwargs %s "
                                "(inputs: %s)" %
                                (op.name, sorted(named), op.input_names))
        return imperative_invoke(op.name, *inputs, out=out, **params)

    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def populate(namespace, filt=None):
    """Install one function per registered op into *namespace*."""
    for name in _reg.list_ops():
        op = _reg.get_op(name)
        if filt and not filt(name):
            continue
        namespace[name] = _make_fn(op)
        # also expose hidden ops without the underscore clash risk
    return namespace


def populate_contrib(namespace):
    """Install ``_contrib_*`` ops under their stripped names (the
    reference exposes them as ``mx.nd.contrib.<name>``,
    python/mxnet/base.py:578 _init_op_module with the contrib prefix)."""
    for name in _reg.list_ops():
        if not name.startswith("_contrib_"):
            continue
        short = name[len("_contrib_"):]
        if short in namespace:
            continue
        namespace[short] = _make_fn(_reg.get_op(name))
    return namespace
