"""nd — imperative NDArray API (reference: python/mxnet/ndarray/)."""

from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,  # noqa
                      zeros_like, ones_like, concatenate, waitall,
                      imperative_invoke, moveaxis, transpose)
from .utils import save, load, save_bytes, load_bytes  # noqa: F401
from . import random  # noqa: F401
from . import register as _register

# Generated op functions (nd.dot, nd.FullyConnected, ...)
_register.populate(globals())

from . import sparse  # noqa: F401  (after op functions exist)

from . import contrib  # noqa: F401,E402  (control flow: foreach/while/cond)
_register.populate_contrib(contrib.__dict__)
from . import image  # noqa: F401,E402
