"""nd.image — device-side image op namespace
(reference: mx.nd.image over src/operator/image/)."""

from ..ops import registry as _reg
from .register import _make_fn

for _name in _reg.list_ops():
    if _name.startswith("_image_"):
        globals()[_name[len("_image_"):]] = _make_fn(_reg.get_op(_name))
del _name, _reg, _make_fn
