"""Python side of the C predict ABI (reference: src/c_api/c_predict_api.cc).

``src/capi/mxtpu_predict.cc`` embeds CPython and calls into this module;
each ``MXPred*`` C function maps onto one method here.  The C++ layer only
marshals raw float buffers and shape tuples — all framework logic
(symbol JSON parsing, param loading, program compilation, forward) stays
on this side of the boundary.  Where the reference routes its predict
API through the eager graph executor (c_predict_api.cc:106
MXPredCreatePartialOut), this surface is a thin client of the serving
subsystem: MXPredCreate loads the model into ``serve.c_registry()``
and MXPredForward dispatches the registry's AOT-compiled bucket
program (mxnet_tpu/serve/, docs/serving.md).
"""

from __future__ import annotations

import functools
import itertools

import numpy as np


#: MXPredCreate handle sequence (registry model names must be unique
#: per live handle)
_PRED_SEQ = itertools.count()


class Predictor(object):
    """One MXPredCreate handle — a thin client of the serve registry.

    The symbol + params are loaded into the process-wide
    :func:`mxnet_tpu.serve.c_registry` as a model whose bucket ladder
    is pinned to the create-time batch, so ``MXPredForward`` runs the
    registry's AOT-compiled bucket program: after create, no trace or
    compile can happen on the C request path (the same contract the
    Python serving surface gives — see docs/serving.md for the full
    C-ABI mapping against the reference ``c_predict_api.cc``)."""

    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_keys, input_shapes):
        import mxnet_tpu as mx
        from mxnet_tpu import serve
        from mxnet_tpu import symbol as sym_mod

        sym = sym_mod.load_json(symbol_json)
        # param files store "arg:name" / "aux:name" prefixed dicts
        # (reference: c_predict_api.cc:153-170)
        arg_params, aux_params = {}, {}
        if param_bytes:
            loaded = mx.nd.load_bytes(param_bytes)
            if not isinstance(loaded, dict):
                raise ValueError(
                    "param file must be a named dict (arg:/aux: keys), "
                    "got a positional list")
            for k, v in loaded.items():
                if k.startswith("arg:"):
                    arg_params[k[4:]] = v
                elif k.startswith("aux:"):
                    aux_params[k[4:]] = v
                else:
                    arg_params[k] = v
        ctx = mx.Context("tpu" if dev_type == 2 else "cpu", dev_id)
        self._ctx = ctx
        shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**shapes)
        self._out_shapes = [tuple(s) for s in out_shapes]
        # unset params predict from zeros, like the reference
        args = {}
        for name, shp in zip(sym.list_arguments(), arg_shapes):
            if name in shapes:
                continue
            args[name] = arg_params[name] if name in arg_params \
                else mx.nd.zeros(shp, ctx=ctx)
        aux = {}
        for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
            aux[name] = aux_params[name] if name in aux_params \
                else mx.nd.zeros(shp, ctx=ctx)
        self._inputs = {k: np.zeros(s, np.float32)
                        for k, s in shapes.items()}
        self._name = "c_pred_%d" % next(_PRED_SEQ)
        self._registry = serve.c_registry()
        batch = shapes[input_keys[0]][0] if input_keys else 1
        # inputs that share the lead input's batch dim ride the (single
        # -rung) ladder; any other input is fixed-shape — multi-input
        # models need not share a leading dim (reference bind semantics)
        bucket = tuple(k for k in input_keys if shapes[k][0] == batch)
        self._pred = self._registry.load(
            self._name, sym, args, aux_params=aux, data_shapes=shapes,
            ladder=serve.BucketLadder(batches=(batch,)), ctx=ctx,
            bucket_inputs=bucket)
        self._outputs = []

    def set_input(self, key, data_bytes, shape):
        arr = np.frombuffer(data_bytes, np.float32).reshape(shape)
        if tuple(shape) != tuple(self._inputs[key].shape):
            raise ValueError(
                "input %r shape %s does not match the bound %s (the "
                "compiled predict program is shape-specialized)"
                % (key, tuple(shape), tuple(self._inputs[key].shape)))
        self._inputs[key] = arr.copy()

    def set_input_flat(self, key, data_bytes):
        """MXPredSetInput: flat float32 buffer, reshaped to the bound
        input's shape (reference: c_predict_api.cc:287 MXPredSetInput)."""
        self.set_input(key, data_bytes, tuple(self._inputs[key].shape))

    def forward(self):
        self._outputs = self._pred.predict(dict(self._inputs))

    def num_outputs(self):
        return len(self._out_shapes)

    def get_output_shape(self, index):
        if self._outputs:
            return tuple(self._outputs[index].shape)
        return self._out_shapes[index]

    def get_output(self, index):
        out = self._outputs[index].asnumpy().astype(np.float32)
        return out.tobytes()

    def close(self):
        """MXPredFree: drop the registry model this handle loaded."""
        from mxnet_tpu.serve import ServeError
        if self._name is not None:
            try:
                self._registry.unload(self._name)
            except ServeError:
                pass    # already unloaded (double free)
            self._name = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: disable=JG006
            pass  # interpreter teardown: registry may be gone already
            #      (finalizers must never raise; not a dispatch path)


def create(symbol_json, param_bytes, dev_type, dev_id, input_keys,
           input_shapes):
    return Predictor(symbol_json, param_bytes, dev_type, dev_id,
                     list(input_keys), list(input_shapes))


def ndlist_load(param_bytes):
    """MXNDListCreate: load an ndarray dict file -> [(name, shape, bytes)].

    Reference: c_predict_api.cc:404 MXNDListCreate."""
    import mxnet_tpu as mx
    loaded = mx.nd.load_bytes(param_bytes)
    if isinstance(loaded, dict):
        items = loaded.items()
    else:
        # unnamed list files get empty keys, like the reference
        items = (("", v) for v in loaded)
    out = []
    for k, v in items:
        a = v.asnumpy().astype(np.float32)
        out.append((k, tuple(a.shape), a.tobytes()))
    return out


# ---------------------------------------------------------------------------
# NDArray + operator-invoke ABI (include/mxtpu/c_api.h over
# src/capi/mxtpu_ndarray.cc; reference surface: include/mxnet/c_api.h
# MXNDArray* / MXImperativeInvoke / MXListAllOpNames / MXNDArraySave).
# Handles on the C side are owned references to the NDArray objects
# returned here.
# ---------------------------------------------------------------------------

# reference mshadow dtype flags (+7 for bfloat16, our extension)
_DTYPE_BY_FLAG = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "int8", 6: "int64", 7: "bfloat16"}
_FLAG_BY_DTYPE = {v: k for k, v in _DTYPE_BY_FLAG.items()}


def nd_create(shape, dtype_flag, dev_type):
    import mxnet_tpu as mx
    del dev_type  # single-device placement; jax owns physical devices
    return mx.nd.zeros(tuple(int(s) for s in shape),
                       dtype=_DTYPE_BY_FLAG[int(dtype_flag)])


def nd_copy_from_bytes(arr, raw):
    import jax.numpy as jnp
    import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
    dt = np.dtype(str(arr.dtype))
    host = np.frombuffer(raw, dtype=dt).reshape(arr.shape)
    arr._data = jnp.asarray(host)
    return True


def nd_to_bytes(arr):
    return np.asarray(arr.asnumpy()).tobytes()


def nd_shape(arr):
    return tuple(int(s) for s in arr.shape)


def nd_dtype(arr):
    return _FLAG_BY_DTYPE[str(arr.dtype)]


@functools.lru_cache(maxsize=None)
def _declared_bools(fn):
    """Parameter names whose declared default is a bool — the only
    params dmlc-style "true"/"false" coercion may apply to.  Cached:
    nd_invoke is the eager C-ABI hot path.

    Returns None ("no signature to consult", i.e. legacy coercion for
    every param) when the signature is unavailable OR takes **kwargs
    (e.g. Custom): params routed through VAR_KEYWORD cannot be
    enumerated, so an empty set would silently disable coercion for
    ALL of that op's params."""
    import inspect
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return None
    return frozenset(p.name for p in sig.parameters.values()
                     if isinstance(p.default, bool))


def _coerce_str_params(str_params, bool_params=None):
    """String param dict -> python values: dmlc-style booleans
    ("true"/"false", any case) for DECLARED-boolean params only, then
    python literals, else the raw string.  Shared by every C surface
    that takes string params.

    *bool_params* is the set of param names declared boolean (see
    `_declared_bools`); with None every param is eligible (legacy
    behavior, for surfaces with no signature to consult).  Limiting the
    coercion matters for string-typed params: a mode string that
    happens to be "true" must stay a string, not become True."""
    import ast
    out = {}
    for k, v in str_params.items():
        low = v.lower() if isinstance(v, str) else v
        if low in ("true", "false"):
            # any-case bool spelling, "True"/"TRUE" included: either a
            # declared-bool param (coerce) or a string-typed one (keep
            # the raw string) — never let literal_eval decide
            out[k] = low == "true" \
                if bool_params is None or k in bool_params else v
            continue
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def nd_invoke(op_name, inputs, str_params):
    """MXImperativeInvoke: string params are parsed exactly like the
    symbol front end parses serialized attrs.

    Donating ops (the fused optimizer updates) MUST run through the
    out= rebinding path: on TPU their input buffers are donated to XLA,
    so without rebinding the C caller's persistent weight/momentum
    handles would point at deleted buffers after one step.  The fused
    ops' convention is that output k reuses the k-th donated input."""
    from mxnet_tpu.ndarray import NDArray
    from mxnet_tpu.ndarray.ndarray import imperative_invoke
    from mxnet_tpu.ops.registry import get_op

    op = get_op(op_name)
    params = _coerce_str_params(str_params, _declared_bools(op.fn))
    out = None
    if op.donate and isinstance(op.num_outputs, int) and \
            len(op.donate) == op.num_outputs:
        out = [inputs[i] for i in op.donate]
    outs = imperative_invoke(op_name, *inputs, out=out, **params)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return [o if isinstance(o, NDArray) else NDArray(o) for o in outs]


def nd_list_ops():
    from mxnet_tpu.ops.registry import list_ops
    return "\n".join(list_ops())


def nd_save(fname, arrs, names):
    import mxnet_tpu as mx
    if names is None:
        mx.nd.save(fname, list(arrs))
    else:
        mx.nd.save(fname, dict(zip(names, arrs)))
    return True


def nd_load(fname):
    import mxnet_tpu as mx
    loaded = mx.nd.load(fname)
    if isinstance(loaded, dict):
        return [(k, v) for k, v in loaded.items()]
    return [(None, v) for v in loaded]


# ---------------------------------------------------------------------------
# Symbolic + executor surface (reference: src/c_api/c_api_symbolic.cc and
# c_api_executor.cc:661 — CreateFromJSON, SimpleBind, Forward, Backward).
# A SymbolHandle is an owned PyObject* of a Symbol; an ExecutorHandle is
# an owned PyObject* of CExecutor below.
# ---------------------------------------------------------------------------

def sym_from_json(json_str):
    from mxnet_tpu.symbol import load_json
    return load_json(json_str)


def sym_to_json(sym):
    return sym.tojson()


def sym_list(sym, which):
    """Newline-joined name listing (same marshaling as nd_list_ops)."""
    if which == "arguments":
        names = sym.list_arguments()
    elif which == "aux":
        names = sym.list_auxiliary_states()
    elif which == "outputs":
        names = sym.list_outputs()
    else:
        raise ValueError("unknown listing %r" % which)
    return "\n".join(names)


class CExecutor(object):
    """One MXExecutorSimpleBind handle.

    Keeps the bound executor; the arg/grad/aux NDArray objects handed to
    the C caller at bind time are the SAME objects the executor reads
    and writes (forward/backward update their ._data in place), so a C
    training loop that mutates args through MXImperativeInvoke's
    donation-rebind path and reads grads after backward just works.
    """

    def __init__(self, ex):
        self.ex = ex


def exec_simple_bind(sym, dev_type, dev_id, grad_req, keys, shapes):
    import mxnet_tpu as mx
    from mxnet_tpu.executor import Executor
    ctx = mx.Context("tpu" if dev_type == 2 else "cpu", dev_id)
    shape_dict = {k: tuple(int(d) for d in s)
                  for k, s in zip(keys, shapes)}
    # the internal dict-based entry point: variable names from the
    # symbol JSON are user-chosen and may collide with simple_bind's
    # own keyword parameters (ctx, grad_req, ...)
    ex = Executor._simple_bind(sym._maybe_partition(), ctx, grad_req,
                               None, shape_dict)
    args = [ex.arg_dict[n] for n in sym.list_arguments()]
    grads = [ex.grad_dict.get(n) for n in sym.list_arguments()]
    auxs = [ex.aux_dict[n] for n in sym.list_auxiliary_states()]
    return CExecutor(ex), args, grads, auxs


def exec_forward(cex, is_train):
    return list(cex.ex.forward(is_train=bool(is_train)))


def exec_backward(cex, head_grads):
    cex.ex.backward(out_grads=head_grads if head_grads else None)
    return True


def exec_outputs(cex):
    return list(cex.ex.outputs)


# ---------------------------------------------------------------------------
# KVStore surface (reference: src/c_api/c_api.cc MXKVStoreCreate/Init/
# Push/Pull + rank/size).  A KVStoreHandle is an owned PyObject* of a
# framework KVStore; keys cross as string lists (the reference's *Ex
# string-key variants).
# ---------------------------------------------------------------------------

def kv_create(kind):
    import mxnet_tpu as mx
    return mx.kv.create(kind)


def kv_init(kv, keys, arrays):
    kv.init(list(keys), list(arrays))
    return True


def kv_push(kv, keys, arrays, priority):
    kv.push(list(keys), list(arrays), priority=int(priority))
    return True


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))
    return True


def kv_type(kv):
    return kv.type


def kv_rank(kv):
    return int(kv.rank)


def kv_group_size(kv):
    return int(kv.num_workers)


# ---------------------------------------------------------------------------
# DataIter surface (reference: src/c_api/c_api.cc MXListDataIters /
# MXDataIterCreateIter / Next / BeforeFirst / GetData / GetLabel /
# GetPadNum).  A DataIterHandle is an owned PyObject* of CDataIter.
# ---------------------------------------------------------------------------

_ITER_FACTORIES = ("MNISTIter", "ImageRecordIter", "CSVIter",
                   "LibSVMIter", "NDArrayIter")


def io_list_iters():
    return "\n".join(_ITER_FACTORIES)


class CDataIter(object):
    """One MXDataIterCreateIter handle: the iterator plus the current
    batch (MXDataIterNext advances; Get* read the cursor batch, the
    reference's cursor contract)."""

    def __init__(self, name, str_params):
        import mxnet_tpu as mx
        if name not in _ITER_FACTORIES:
            raise ValueError("unknown data iter %r (have %s)"
                             % (name, ", ".join(_ITER_FACTORIES)))
        factory = getattr(mx.io, name)
        sig_fn = factory.__init__ if isinstance(factory, type) else factory
        self._it = factory(**_coerce_str_params(
            str_params, _declared_bools(sig_fn)))
        self._batch = None

    def next(self):
        try:
            self._batch = next(self._it)
            return 1
        except StopIteration:
            self._batch = None
            return 0

    def before_first(self):
        self._it.reset()
        self._batch = None
        return True

    def data(self):
        return self._batch.data[0]

    def label(self):
        return self._batch.label[0]

    def pad(self):
        return int(self._batch.pad or 0)


def io_create(name, keys, vals):
    return CDataIter(name, dict(zip(keys, vals)))


def io_next(it):
    return it.next()


def io_before_first(it):
    return it.before_first()


def io_data(it):
    return it.data()


def io_label(it):
    return it.label()


def io_pad(it):
    return it.pad()
