"""Manual model parallelism — honoring ``group2ctx`` / ``ctx_group``.

Reference capability: `src/executor/graph_executor.cc:897-906`
(`AssignContext` maps each node's ``ctx_group`` attr to a device;
cross-device edges become `kCrossDeviceCopy` ops, `:1347-1351`) with the
Python surface `symbol.simple_bind(group2ctx=...)`
(`python/mxnet/symbol/symbol.py:1290-1439`).

TPU-native design: the graph is partitioned into maximal same-device
segments in topological order.  Each segment compiles to one jitted
function pinned to its device (arrays are committed there, so XLA runs
the program on that chip); boundary values are `jax.device_put`
transfers — the explicit equivalent of kCrossDeviceCopy.  Backward
chains the per-segment VJPs in reverse, transferring cotangents across
the same boundaries.  Because JAX dispatch is async, consecutive
segments on different devices overlap exactly like the reference's
engine-scheduled cross-device pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context


def assign_contexts(symbol, group2ctx, default_ctx):
    """Per-node Context from ctx_group attrs (reference AssignContext).
    Variables inherit the context of their first consumer."""
    group2ctx = {k: Context(v) for k, v in (group2ctx or {}).items()}
    order = symbol._topo()
    node_ctx = {}
    for node in order:
        grp = node.attrs.get("ctx_group")
        if grp is not None:
            if grp not in group2ctx:
                raise MXNetError(
                    "ctx_group %r has no entry in group2ctx %s"
                    % (grp, sorted(group2ctx)))
            node_ctx[id(node)] = group2ctx[grp]
        elif not node.is_var:
            node_ctx[id(node)] = default_ctx
    for node in order:
        if node.is_var:
            continue
        for src, _ in node.inputs:
            if src.is_var and id(src) not in node_ctx:
                node_ctx[id(src)] = node_ctx[id(node)]
    for node in order:
        node_ctx.setdefault(id(node), default_ctx)
    return node_ctx


class _Segment:
    __slots__ = ("nodes", "ctx", "in_entries", "out_entries", "fn",
                 "index")

    def __init__(self, nodes, ctx, index):
        self.nodes = nodes
        self.ctx = ctx
        self.index = index


def _partition(symbol, node_ctx):
    """Maximal same-context runs of op nodes in topo order."""
    order = [n for n in symbol._topo() if not n.is_var]
    segments = []
    for node in order:
        ctx = node_ctx[id(node)]
        if segments and segments[-1].ctx == ctx:
            segments[-1].nodes.append(node)
        else:
            segments.append(_Segment([node], ctx, len(segments)))
    return segments


def build_grouped_eval(symbol, group2ctx, default_ctx, training,
                       aux_names):
    """Compile the segment chain.  Returns
    run(arg_map, aux_map, key, want_vjp) ->
        (outputs, aux_updates, vjp_chain_or_None)."""
    node_ctx = assign_contexts(symbol, group2ctx, default_ctx)
    segments = _partition(symbol, node_ctx)
    out_entries = [(id(n), i) for n, i in symbol._outputs]
    aux_set = set(aux_names)

    # which entries cross segment boundaries
    producer_seg = {}
    for seg in segments:
        for node in seg.nodes:
            for i in range(node.num_outputs()):
                producer_seg[(id(node), i)] = seg.index

    var_nodes = {}
    for node in symbol._topo():
        if node.is_var:
            var_nodes[(id(node), 0)] = node

    aux_update_entries = {}   # aux name -> entry of updated value
    for seg in segments:
        needed = set()
        produced = set()
        for node in seg.nodes:
            for (src, idx) in node.inputs:
                e = (id(src), idx)
                if e not in produced:
                    needed.add(e)
            for i in range(node.num_outputs()):
                produced.add((id(node), i))
            if training and node.op.aux_states:
                for in_idx, out_idx in node.op.aux_states.items():
                    src, _ = node.inputs[in_idx]
                    if src.is_var and src.name in aux_set:
                        aux_update_entries[src.name] = (id(node), out_idx)
        seg.in_entries = sorted(needed)
        exported = set(out_entries) | set(aux_update_entries.values())
        for later in segments[seg.index + 1:]:
            for node in later.nodes:
                for (src, idx) in node.inputs:
                    exported.add((id(src), idx))
        seg.out_entries = sorted(e for e in produced if e in exported)

        seg.fn = _make_segment_fn(seg, training)

    def run(arg_map, aux_map, key, want_vjp):
        env = {}
        for e, node in var_nodes.items():
            name = node.name
            if name in arg_map:
                v = arg_map[name]
            elif name in aux_map:
                v = aux_map[name]
            else:
                raise MXNetError("unbound variable %r" % name)
            env[e] = jax.device_put(v, node_ctx[id(node)].jax_device)
        vjps = []
        for seg in segments:
            dev = seg.ctx.jax_device
            ins = tuple(jax.device_put(env[e], dev)
                        for e in seg.in_entries)
            # the executor key is committed to the bind ctx device; the
            # folded per-segment key must live on the SEGMENT's device
            # or the jit sees a two-device argument assignment
            sub = jax.device_put(jax.random.fold_in(key, seg.index), dev)
            if want_vjp:
                outs, vjp = jax.vjp(lambda *a: seg.fn(sub, *a), *ins)
                vjps.append((seg, vjp,
                             [(o.shape, o.dtype) for o in outs]))
            else:
                outs = seg.fn(sub, *ins)
            env.update(zip(seg.out_entries, outs))
        outputs = [env[e] for e in out_entries]
        aux_updates = {n: env[e] for n, e in aux_update_entries.items()}
        return outputs, aux_updates, (vjps if want_vjp else None)

    def backward(env_run, out_cots):
        """Chain per-segment VJPs in reverse.  env_run = (vjps from run);
        out_cots aligned with symbol outputs.  Returns {var_name: grad}."""
        vjps = env_run
        cot = {}
        for e, c in zip(out_entries, out_cots):
            if c is not None:
                cot[e] = cot.get(e, 0) + c
        var_grads = {}
        for seg, vjp, out_avals in reversed(vjps):
            seg_cots = []
            need = False
            for e, (shape, dtype) in zip(seg.out_entries, out_avals):
                c = cot.pop(e, None)
                if c is None:
                    seg_cots.append(None)
                else:
                    need = True
                    seg_cots.append(c.astype(dtype))
            if not need:
                continue
            # materialize Nones as zeros (vjp wants the full pytree) and
            # commit every cotangent to the SEGMENT's device — the
            # caller's cotangents arrive on the bind-ctx device, and a
            # vjp whose residuals live elsewhere rejects the mix
            seg_dev = seg.ctx.jax_device
            seg_cots = tuple(
                jax.device_put(
                    c if c is not None else jnp.zeros(shape, dtype),
                    seg_dev)
                for c, (shape, dtype) in zip(seg_cots, out_avals))
            in_cots = vjp(seg_cots)
            for e, c in zip(seg.in_entries, in_cots):
                if e in var_nodes:
                    name = var_nodes[e].name
                    dev = node_ctx[id(var_nodes[e])].jax_device
                    c = jax.device_put(c, dev)
                    if name in var_grads:
                        var_grads[name] = var_grads[name] + c
                    else:
                        var_grads[name] = c
                else:
                    prod_dev = segments[producer_seg[e]].ctx.jax_device
                    c = jax.device_put(c, prod_dev)
                    if e in cot:
                        cot[e] = cot[e] + c
                    else:
                        cot[e] = c
        return var_grads

    return run, backward, segments


def _make_segment_fn(seg, training):
    """Jitted pure function for one segment:
    fn(key, *in_values) -> out_values."""
    nodes = seg.nodes
    in_entries = seg.in_entries
    out_entries = seg.out_entries

    def fn(key, *ins):
        vals = dict(zip(in_entries, ins))
        for pos, node in enumerate(nodes):
            op = node.op
            arrs = [vals[(id(s), i)] for (s, i) in node.inputs]
            params = node.params
            if "training" in op.param_names:
                params = dict(params, training=training)
            if op.needs_rng:
                sub = jax.random.fold_in(key, pos)
                out = op.fn(sub, *arrs, **params)
            else:
                out = op.fn(*arrs, **params)
            if not isinstance(out, tuple):
                out = (out,)
            for i, o in enumerate(out):
                vals[(id(node), i)] = o
        return tuple(vals[e] for e in out_entries)

    return jax.jit(fn)
