"""sym — symbolic graph API (reference: python/mxnet/symbol/)."""

from .symbol import (Symbol, var, Variable, Group, load,  # noqa
                     load_json, AttrScope)
from . import register as _register

_register.populate(globals())

zeros = globals()["_zeros"]
ones = globals()["_ones"]

from . import contrib  # noqa: F401,E402  (control flow: foreach/while/cond)
_register.populate_contrib(contrib.__dict__)
from . import image  # noqa: F401,E402
