"""Generate the ``sym.*`` op functions (reference:
python/mxnet/symbol/register.py)."""

from __future__ import annotations

from ..ops import registry as _reg
from .symbol import Symbol, _sym_invoke


def _make_fn(op):
    def fn(*args, name=None, attr=None, **kwargs):
        inputs = []
        pos_params = []
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
            else:
                pos_params.append(a)
        params = {}
        named = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                named[k] = v
            else:
                params[k] = v
        if pos_params:
            free = [p for p in op.param_names if p not in params]
            if len(pos_params) > len(free):
                raise TypeError("%s: too many positional arguments" %
                                op.name)
            for p, v in zip(free, pos_params):
                params[p] = v
        if named:
            input_names = op.input_names_for(params)
            # reference convention: every op's first input is addressable
            # as ``data=`` (e.g. sym.Flatten(data=x) where the op's own
            # input name is 'x')
            if "data" in named and "data" not in input_names \
                    and input_names and input_names[0] not in named:
                named[input_names[0]] = named.pop("data")
            by_name = {}
            for i, s in enumerate(inputs):
                by_name[i] = s
            merged = list(inputs)
            for nm in input_names[len(inputs):]:
                if nm in named:
                    merged.append(named.pop(nm))
                else:
                    merged.append(None)  # placeholder -> auto var
            while merged and merged[-1] is None:
                merged.pop()
            if named:
                raise TypeError("%s got unexpected Symbol kwargs %s "
                                "(inputs: %s)" %
                                (op.name, sorted(named), op.input_names))
            inputs = merged
        return _sym_invoke_padded(op, inputs, params, name, attr)

    fn.__name__ = op.name
    fn.__doc__ = op.doc
    return fn


def _sym_invoke_padded(op, inputs, params, name, attr):
    # None placeholders (skipped named inputs) become auto-created vars
    from .symbol import Node, _NameManager, AttrScope
    params = {k: v for k, v in params.items() if v is not None}
    if name is None:
        name = _NameManager.get().fresh(op.name)
    scope_attrs = AttrScope.current_attrs()
    input_names = op.input_names_for(params)
    entries = []
    for i, s in enumerate(inputs):
        if s is None:
            nm = input_names[i] if i < len(input_names) else "in%d" % i
            entries.append((Node(None, "%s_%s" % (name, nm),
                                 attrs=dict(scope_attrs)), 0))
        else:
            entries.append(s._outputs[0])
    if input_names and len(entries) < len(input_names):
        for nm in input_names[len(entries):]:
            entries.append((Node(None, "%s_%s" % (name, nm),
                                 attrs=dict(scope_attrs)), 0))
    node_attrs = dict(scope_attrs)
    node_attrs.update(attr or {})
    node = Node(op, name, params=params, inputs=entries, attrs=node_attrs)
    n_vis = op.n_visible(params)
    return Symbol([(node, i) for i in range(n_vis)])


def populate(namespace, filt=None):
    for name in _reg.list_ops():
        op = _reg.get_op(name)
        if filt and not filt(name):
            continue
        namespace[name] = _make_fn(op)
    return namespace


def populate_contrib(namespace):
    """``_contrib_*`` ops under stripped names, as ``mx.sym.contrib.<name>``
    (reference: python/mxnet/base.py:578 _init_op_module)."""
    for name in _reg.list_ops():
        if not name.startswith("_contrib_"):
            continue
        short = name[len("_contrib_"):]
        if short in namespace:
            continue
        namespace[short] = _make_fn(_reg.get_op(name))
    return namespace
