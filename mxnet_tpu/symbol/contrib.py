"""Symbolic control flow (reference: python/mxnet/symbol/contrib.py
foreach:215, while_loop:378, cond:601).

The body/cond/func callables are traced once with placeholder variables;
the traced subgraph becomes a static parameter of a `_foreach` /
`_while_loop` / `_cond` node (ops/control_flow.py), which lowers to
`lax.scan`/`lax.cond` inside the enclosing XLA program.  Outer variables
captured by the body join the node's inputs so the executor binds them.
"""

from __future__ import annotations

from . import symbol as sym_mod
from .symbol import Symbol, Group, var, _sym_invoke

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _maybe_scalar(lst, was_scalar):
    return lst[0] if was_scalar and len(lst) == 1 else lst


def _var_nodes(subgraph):
    return {n.name: Symbol([(n, 0)])
            for n in subgraph._topo() if n.is_var}


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body(data_t, states) -> (outputs, new_states)`` over axis 0
    of *data*.  Returns (outputs, final_states)."""
    data_l = _as_list(data)
    states_l = _as_list(init_states)
    data_scalar = not isinstance(data, (list, tuple))
    states_scalar = not isinstance(init_states, (list, tuple))
    data_names = ["__foreach_data%d" % i for i in range(len(data_l))]
    state_names = ["__foreach_state%d" % i for i in range(len(states_l))]
    data_vars = [var(n) for n in data_names]
    state_vars = [var(n) for n in state_names]
    outs, new_states = body(_maybe_scalar(data_vars, data_scalar),
                            _maybe_scalar(state_vars, states_scalar))
    outs_scalar = not isinstance(outs, (list, tuple))
    outs_l = _as_list(outs)
    new_states_l = _as_list(new_states)
    if len(new_states_l) != len(states_l):
        raise ValueError("body must return as many states as init_states")
    sub = Group(outs_l + new_states_l) if len(outs_l + new_states_l) > 1 \
        else (outs_l + new_states_l)[0]
    bound = set(data_names + state_names)
    closure_names = [a for a in sub.list_arguments() if a not in bound]
    vmap = _var_nodes(sub)
    closure_syms = [vmap[n] for n in closure_names]
    out = _sym_invoke(
        "_foreach", data_l + states_l + closure_syms,
        {"subgraph": sub, "n_data": len(data_l),
         "n_states": len(states_l), "n_outputs": len(outs_l),
         "data_names": tuple(data_names),
         "state_names": tuple(state_names),
         "closure_names": tuple(closure_names)},
        name=name)
    outputs = [out[i] for i in range(len(outs_l))]
    finals = [out[len(outs_l) + i] for i in range(len(states_l))]
    # scalar-vs-list of the result mirrors what the body returned, same
    # as the imperative ndarray.contrib.foreach
    return (_maybe_scalar(outputs, outs_scalar),
            _maybe_scalar(finals, states_scalar))


def while_loop(cond, func, loop_vars, max_iterations, name="while_loop"):
    """Run ``func(*loop_vars) -> (outputs, new_loop_vars)`` while
    ``cond(*loop_vars)`` is true, at most max_iterations times.
    Outputs are stacked over an axis-0 of size max_iterations (unexecuted
    rows are zeros); returns (outputs, final_loop_vars)."""
    lvars = _as_list(loop_vars)
    lscalar = not isinstance(loop_vars, (list, tuple))
    lnames = ["__while_var%d" % i for i in range(len(lvars))]
    lvs = [var(n) for n in lnames]
    cond_out = cond(*lvs)
    outs, new_vars = func(*lvs)
    outs_l = _as_list(outs)
    new_l = _as_list(new_vars)
    if len(new_l) != len(lvars):
        raise ValueError("func must return as many loop_vars as given")
    cond_sub = cond_out
    func_sub = Group(outs_l + new_l) if len(outs_l + new_l) > 1 \
        else (outs_l + new_l)[0]
    bound = set(lnames)
    cond_clo = [a for a in cond_sub.list_arguments() if a not in bound]
    func_clo = [a for a in func_sub.list_arguments() if a not in bound]
    cmap = _var_nodes(cond_sub)
    fmap = _var_nodes(func_sub)
    out = _sym_invoke(
        "_while_loop",
        lvars + [cmap[n] for n in cond_clo] + [fmap[n] for n in func_clo],
        {"cond_graph": cond_sub, "func_graph": func_sub,
         "max_iterations": int(max_iterations),
         "n_loop_vars": len(lvars), "n_outputs": len(outs_l),
         "loop_var_names": tuple(lnames),
         "cond_closure_names": tuple(cond_clo),
         "func_closure_names": tuple(func_clo)},
        name=name)
    outputs = [out[i] for i in range(len(outs_l))]
    finals = [out[len(outs_l) + i] for i in range(len(lvars))]
    return (outputs[0] if len(outputs) == 1 else outputs,
            _maybe_scalar(finals, lscalar))


def cond(pred, then_func, else_func, name="cond"):
    """Branch: evaluates then_func() or else_func() based on scalar
    ``pred`` (a Symbol); both must produce the same output spec."""
    then_out = then_func()
    else_out = else_func()
    then_l = _as_list(then_out)
    else_l = _as_list(else_out)
    if len(then_l) != len(else_l):
        raise ValueError("then/else must return the same number of "
                         "outputs")
    tscalar = not isinstance(then_out, (list, tuple))
    then_sub = Group(then_l) if len(then_l) > 1 else then_l[0]
    else_sub = Group(else_l) if len(else_l) > 1 else else_l[0]
    pred_names = pred.list_arguments()
    then_names = then_sub.list_arguments()
    else_names = else_sub.list_arguments()
    pmap, tmap, emap = (_var_nodes(pred), _var_nodes(then_sub),
                        _var_nodes(else_sub))
    out = _sym_invoke(
        "_cond",
        [pmap[n] for n in pred_names] + [tmap[n] for n in then_names] +
        [emap[n] for n in else_names],
        {"pred_graph": pred, "then_graph": then_sub,
         "else_graph": else_sub, "n_outputs": len(then_l),
         "pred_names": tuple(pred_names),
         "then_names": tuple(then_names),
         "else_names": tuple(else_names)},
        name=name)
    outputs = [out[i] for i in range(len(then_l))]
    return _maybe_scalar(outputs, tscalar)


def rand_zipfian(true_classes, num_sampled, range_max):
    """Symbolic log-uniform candidate sampler (reference:
    python/mxnet/symbol/contrib.py rand_zipfian); same math as the
    ndarray version, built from symbolic ops."""
    import math
    import mxnet_tpu.symbol as sym_pkg

    log_range = math.log(range_max + 1)
    u = sym_pkg._random_uniform(low=0.0, high=1.0,
                                shape=(int(num_sampled),))
    sampled = sym_pkg.floor(sym_pkg.exp(u * log_range) - 1.0)
    sampled = sampled - sym_pkg.floor(
        sampled / range_max) * range_max    # mod range_max

    def expected(cls):
        p = (sym_pkg.log((cls + 2.0) / (cls + 1.0))) / log_range
        return p * float(num_sampled)

    return sampled, expected(true_classes), expected(sampled)
