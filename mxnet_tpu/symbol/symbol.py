"""Symbol — the symbolic graph API.

Reference: ``python/mxnet/symbol/symbol.py`` (2,970 LoC) over the nnvm graph
IR (``3rdparty/tvm/nnvm``).

TPU-native design: a Symbol is a tiny DAG of (op, params, inputs) nodes.
There are no graph passes for memory planning, inplace detection or op
fusion — binding a Symbol compiles the *whole graph* into one XLA executable
(the reference's bulk-exec concept taken to its limit, SURVEY.md §7 step 4),
and XLA owns those optimizations.  Shape/type inference runs either through
per-op rules (so parameter shapes can be inferred bottom-up like the
reference's FInferShape) or ``jax.eval_shape`` over the traced graph.
"""

from __future__ import annotations

import json
import ast
import threading

import numpy as _np

from ..base import np_dtype, dtype_name, MXNetError
from ..ops import registry as _reg

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]


class _NameManager:
    _tls = threading.local()

    @classmethod
    def get(cls):
        if not hasattr(cls._tls, "inst"):
            cls._tls.inst = cls()
        return cls._tls.inst

    def __init__(self):
        self.counts = {}

    def fresh(self, hint):
        hint = hint.lower().lstrip("_")
        n = self.counts.get(hint, 0)
        self.counts[hint] = n + 1
        return "%s%d" % (hint, n)


class AttrScope:
    """Scoped symbol attributes (reference: python/mxnet/attribute.py) —
    ops/vars created inside ``with AttrScope(ctx_group='dev1'):`` carry
    the attrs; this is how manual model-parallel groups are declared."""

    _tls = threading.local()

    def __init__(self, **attrs):
        self._attrs = attrs

    @classmethod
    def current_attrs(cls):
        stack = getattr(cls._tls, "stack", None)
        if not stack:
            return {}
        merged = {}
        for scope in stack:
            merged.update(scope._attrs)
        return merged

    def __enter__(self):
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        self._tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        self._tls.stack.pop()


class Node:
    """One graph node: a variable (op is None) or an op invocation."""

    __slots__ = ("op", "name", "params", "inputs", "attrs")

    def __init__(self, op, name, params=None, inputs=(), attrs=None):
        self.op = op                  # ops.registry.Op or None for variables
        self.name = name
        self.params = dict(params or {})
        self.inputs = list(inputs)    # [(Node, out_idx), ...]
        self.attrs = dict(attrs or {})  # user attrs (ctx_group, lr_mult, ...)

    @property
    def is_var(self):
        return self.op is None

    def num_outputs(self):
        return 1 if self.is_var else self.op.n_out(self.params)


class Symbol:
    """An ordered list of graph output entries."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(Node, out_idx)]

    # -- composition -------------------------------------------------------
    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        return Symbol([self._outputs[idx]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    @property
    def name(self):
        node, idx = self._outputs[0]
        return node.name

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(kwargs)

    # -- arithmetic (mirrors NDArray operator set) -------------------------
    def __add__(self, other):
        return _sym_binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _sym_binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _sym_invoke("_rminus_scalar", [self], {"scalar": float(other)})

    def __mul__(self, other):
        return _sym_binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _sym_binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _sym_invoke("_rdiv_scalar", [self], {"scalar": float(other)})

    def __pow__(self, other):
        return _sym_binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return _sym_invoke("negative", [self], {})

    def __eq__(self, other):
        return _sym_binary("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        return _sym_binary("broadcast_not_equal", "_not_equal_scalar", self,
                           other)

    def __gt__(self, other):
        return _sym_binary("broadcast_greater", "_greater_scalar", self,
                           other)

    def __ge__(self, other):
        return _sym_binary("broadcast_greater_equal",
                           "_greater_equal_scalar", self, other)

    def __lt__(self, other):
        return _sym_binary("broadcast_lesser", "_lesser_scalar", self, other)

    def __le__(self, other):
        return _sym_binary("broadcast_lesser_equal", "_lesser_equal_scalar",
                           self, other)

    __hash__ = object.__hash__

    def __repr__(self):
        return "<Symbol %s>" % ", ".join(
            "%s[%d]" % (n.name, i) for n, i in self._outputs)

    # -- op methods (mirror of NDArray's method set) -----------------------
    def sum(self, axis=None, keepdims=False):
        return _sym_invoke("sum", [self], {"axis": axis,
                                           "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _sym_invoke("mean", [self], {"axis": axis,
                                            "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _sym_invoke("max", [self], {"axis": axis,
                                           "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _sym_invoke("min", [self], {"axis": axis,
                                           "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return _sym_invoke("prod", [self], {"axis": axis,
                                            "keepdims": keepdims})

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _sym_invoke("Reshape", [self],
                           {"shape": tuple(shape),
                            "reverse": kwargs.get("reverse", False)})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _sym_invoke("transpose", [self], {"axes": axes or None})

    def flatten(self):
        return _sym_invoke("Flatten", [self], {})

    def expand_dims(self, axis):
        return _sym_invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _sym_invoke("squeeze", [self], {"axis": axis})

    def swapaxes(self, dim1, dim2):
        return _sym_invoke("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def slice_axis(self, axis, begin, end):
        return _sym_invoke("slice_axis", [self],
                           {"axis": axis, "begin": begin, "end": end})

    def clip(self, a_min=None, a_max=None):
        return _sym_invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _sym_invoke("dot", [self, other],
                           {"transpose_a": transpose_a,
                            "transpose_b": transpose_b})

    def exp(self):
        return _sym_invoke("exp", [self], {})

    def log(self):
        return _sym_invoke("log", [self], {})

    def sqrt(self):
        return _sym_invoke("sqrt", [self], {})

    def square(self):
        return _sym_invoke("square", [self], {})

    def abs(self):
        return _sym_invoke("abs", [self], {})

    def sign(self):
        return _sym_invoke("sign", [self], {})

    def relu(self):
        return _sym_invoke("relu", [self], {})

    def sigmoid(self):
        return _sym_invoke("sigmoid", [self], {})

    def tanh(self):
        return _sym_invoke("tanh", [self], {})

    def softmax(self, axis=-1):
        return _sym_invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return _sym_invoke("log_softmax", [self], {"axis": axis})

    def argmax(self, axis=None, keepdims=False):
        return _sym_invoke("argmax", [self], {"axis": axis,
                                              "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _sym_invoke("argmin", [self], {"axis": axis,
                                              "keepdims": keepdims})

    def astype(self, dtype):
        from ..base import dtype_name
        return _sym_invoke("Cast", [self], {"dtype": dtype_name(dtype)})

    def take(self, indices, axis=0, mode="clip"):
        return _sym_invoke("take", [self, indices],
                           {"axis": axis, "mode": mode})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _sym_invoke("SliceChannel", [self],
                           {"num_outputs": num_outputs, "axis": axis,
                            "squeeze_axis": squeeze_axis})

    def norm(self, ord=2, axis=None, keepdims=False):
        return _sym_invoke("norm", [self], {"ord": ord, "axis": axis,
                                            "keepdims": keepdims})

    # -- graph queries -----------------------------------------------------
    def _topo(self):
        """Post-order DFS (matches nnvm::Graph topo order)."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for (src, _i) in node.inputs:
                visit(src)
            order.append(node)

        for (n, _i) in self._outputs:
            visit(n)
        return order

    def _aux_var_ids(self):
        aux = set()
        for node in self._topo():
            if node.is_var:
                continue
            for in_idx, _out_idx in node.op.aux_states.items():
                if in_idx < len(node.inputs):
                    src, _ = node.inputs[in_idx]
                    if src.is_var:
                        aux.add(id(src))
        return aux

    def list_arguments(self):
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) in aux]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_var]

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_var:
                names.append(node.name)
            elif node.num_outputs() == 1:
                names.append(node.name + "_output")
            else:
                names.append("%s_output%d" % (node.name, idx))
        return names

    def get_internals(self):
        outs = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        known = {}
        if args:
            for name, shp in zip(self.list_arguments(), args):
                if shp is not None:
                    known[name] = tuple(shp)
        known.update({k: tuple(v) for k, v in kwargs.items()})
        shapes = _infer_shapes(self, known, partial=partial)
        if shapes is None:
            return None, None, None
        node_sh, var_sh = shapes
        arg_shapes = [var_sh.get(n) for n in self.list_arguments()]
        aux_shapes = [var_sh.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [node_sh.get((id(n), i)) for n, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        known = {}
        if args:
            for name, dt in zip(self.list_arguments(), args):
                if dt is not None:
                    known[name] = np_dtype(dt)
        known.update({k: np_dtype(v) for k, v in kwargs.items()})
        # default everything unknown to float32 (reference behavior)
        arg_types = [known.get(n, _np.dtype("float32"))
                     for n in self.list_arguments()]
        aux_types = [known.get(n, _np.dtype("float32"))
                     for n in self.list_auxiliary_states()]
        # outputs via eval_shape with inferred shapes unknown -> give up to
        # float32; refined during bind
        out_types = [_np.dtype("float32")] * len(self._outputs)
        return arg_types, out_types, aux_types

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """Graph JSON in the reference's schema (nodes/arg_nodes/heads —
        python/mxnet/symbol/symbol.py save; values stringified like dmlc
        params)."""
        order = self._topo()
        nid = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            entry = {
                "op": "null" if n.is_var else n.op.name,
                "name": n.name,
                "inputs": [[nid[id(s)], i, 0] for (s, i) in n.inputs],
            }
            attrs = {k: _stringify(v) for k, v in n.params.items()}
            if n.attrs:
                attrs.update({"__%s__" % k: _stringify(v)
                              for k, v in n.attrs.items()})
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": [nid[id(n)] for n in order if n.is_var],
            "node_row_ptr": list(range(len(order) + 1)),
            "heads": [[nid[id(n)], i, 0] for (n, i) in self._outputs],
            "attrs": {"mxnet_version": ["int", 10301],
                      "framework": ["str", "mxnet_tpu"]},
        }, indent=2)

    def save(self, fname):
        # atomic: a preemption mid-write must not tear the only copy
        # of a checkpoint's graph (resilience subsystem)
        from ..resilience.checkpoint import atomic_write
        atomic_write(fname, self.tojson().encode("utf-8"))

    # -- binding -----------------------------------------------------------
    def _maybe_partition(self):
        """Apply the env-selected subgraph backend at bind time
        (reference: MXNET_SUBGRAPH_BACKEND consulted by the executor's
        PartitionGraph pass)."""
        from ..config import get_env
        backend = get_env("MXNET_SUBGRAPH_BACKEND")
        if not backend:
            return self
        from ..subgraph import partition_graph, list_subgraph_backends
        if backend not in list_subgraph_backends():
            import warnings
            warnings.warn(
                "MXNET_SUBGRAPH_BACKEND=%r is not a registered backend "
                "(known: %s); partitioning skipped"
                % (backend, list_subgraph_backends()))
            return self
        return partition_graph(self, backend)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        return Executor._simple_bind(self._maybe_partition(), ctx,
                                     grad_req, type_dict,
                                     kwargs, shared_exec=shared_exec,
                                     group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor._bind(self._maybe_partition(), ctx, args,
                              args_grad, grad_req,
                              aux_states, group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # -- misc --------------------------------------------------------------
    def tojson_str(self):
        return self.tojson()


def _stringify(v):
    if isinstance(v, str):
        return v
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _parse_attr(v):
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        if v in ("True", "False"):
            return v == "True"
        return v


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference: symbol.py var/Variable)."""
    attrs = dict(AttrScope.current_attrs())
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = dtype_name(dtype)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            init.__class__.__name__
    if stype is not None:
        attrs["__storage_type__"] = stype
    attrs.update(kwargs)
    return Symbol([(Node(None, name, attrs=attrs), 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for entry in data["nodes"]:
        attrs = entry.get("attrs", entry.get("param", {})) or {}
        params = {}
        uattrs = {}
        for k, v in attrs.items():
            if k.startswith("__") and k.endswith("__"):
                uattrs[k[2:-2]] = _parse_attr(v)
            else:
                params[k] = _parse_attr(v)
        if entry["op"] == "null":
            node = Node(None, entry["name"], attrs=dict(params, **uattrs))
        else:
            op = _reg.get_op(entry["op"])
            node = Node(op, entry["name"], params=params, attrs=uattrs)
        node.inputs = [(nodes[i], j) for i, j, _ in entry["inputs"]]
        nodes.append(node)
    heads = [(nodes[i], j) for i, j, _ in data["heads"]]
    return Symbol(heads)


# ---------------------------------------------------------------------------
# symbolic op invocation
# ---------------------------------------------------------------------------


def _sym_invoke(op_name, sym_inputs, params, name=None, attr=None):
    op = _reg.get_op(op_name)
    params = {k: v for k, v in params.items() if v is not None}
    if name is None:
        name = _NameManager.get().fresh(op.name)
    input_names = op.input_names_for(params)
    inputs = []
    for i, s in enumerate(sym_inputs):
        if s is None:
            continue
        if len(s._outputs) != 1:
            raise ValueError("op inputs must be single-output symbols")
        inputs.append(s._outputs[0])
    # auto-create missing declared inputs as variables (reference behavior:
    # sym.Convolution(data=d, ...) creates convN_weight / convN_bias)
    if input_names and len(inputs) < len(input_names):
        scope_attrs = AttrScope.current_attrs()
        for nm in input_names[len(inputs):]:
            inputs.append((Node(None, "%s_%s" % (name, nm),
                                attrs=dict(scope_attrs)), 0))
    node_attrs = dict(AttrScope.current_attrs())
    node_attrs.update(attr or {})
    node = Node(op, name, params=params, inputs=inputs, attrs=node_attrs)
    n_vis = op.n_visible(params)
    return Symbol([(node, i) for i in range(n_vis)])


def _sym_binary(op_name, scalar_op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return _sym_invoke(op_name, [lhs, rhs], {})
    return _sym_invoke(scalar_op, [lhs], {"scalar": float(rhs)})


# ---------------------------------------------------------------------------
# shape inference
# ---------------------------------------------------------------------------

# per-op inference rules for ops whose parameter shapes must be deduced
# bottom-up (reference: FInferShape attrs).  rule(params, in_shapes) ->
# (in_shapes, out_shapes); in_shapes entries may start as None.

_SHAPE_RULES = {}


def shape_rule(name):
    def _reg_rule(fn):
        _SHAPE_RULES[name] = fn
        return fn
    return _reg_rule


@shape_rule("FullyConnected")
def _fc_shape(params, ins):
    data, weight = ins[0], ins[1]
    nh = int(params.get("num_hidden", 0))
    flatten = params.get("flatten", True)
    if data is not None:
        in_units = 1
        if flatten:
            for d in data[1:]:
                in_units *= d
            out = (data[0], nh)
        else:
            in_units = data[-1]
            out = tuple(data[:-1]) + (nh,)
        ins = list(ins)
        ins[1] = (nh, in_units)
        if len(ins) > 2:
            ins[2] = (nh,)
        return ins, [out]
    return ins, [None]


@shape_rule("Convolution")
def _conv_shape(params, ins):
    data = ins[0]
    kernel = tuple(params.get("kernel", ()))
    nf = int(params.get("num_filter", 0))
    ng = int(params.get("num_group", 1))
    nd = len(kernel)
    stride = params.get("stride") or (1,) * nd
    dilate = params.get("dilate") or (1,) * nd
    pad = params.get("pad") or (0,) * nd
    if data is not None:
        c = data[1]
        ins = list(ins)
        ins[1] = (nf, c // ng) + kernel
        if len(ins) > 2:
            ins[2] = (nf,)
        spatial = []
        for i in range(nd):
            eff_k = (kernel[i] - 1) * dilate[i] + 1
            spatial.append((data[2 + i] + 2 * pad[i] - eff_k) // stride[i]
                           + 1)
        return ins, [(data[0], nf) + tuple(spatial)]
    return ins, [None]


@shape_rule("Deconvolution")
def _deconv_shape(params, ins):
    data = ins[0]
    kernel = tuple(params.get("kernel", ()))
    nf = int(params.get("num_filter", 0))
    ng = int(params.get("num_group", 1))
    nd = len(kernel)
    stride = params.get("stride") or (1,) * nd
    dilate = params.get("dilate") or (1,) * nd
    pad = params.get("pad") or (0,) * nd
    adj = params.get("adj") or (0,) * nd
    if data is not None:
        c = data[1]
        ins = list(ins)
        ins[1] = (c, nf // ng) + kernel
        if len(ins) > 2:
            ins[2] = (nf,)
        spatial = []
        for i in range(nd):
            eff_k = (kernel[i] - 1) * dilate[i] + 1
            spatial.append((data[2 + i] - 1) * stride[i] - 2 * pad[i] +
                           eff_k + adj[i])
        return ins, [(data[0], nf) + tuple(spatial)]
    return ins, [None]


def _chan_param_shape(params, ins, n_extra):
    data = ins[0]
    axis = int(params.get("axis", 1))
    if data is not None:
        c = data[axis % len(data)]
        ins = list(ins)
        for i in range(1, 1 + n_extra):
            if i < len(ins):
                ins[i] = (c,)
        return ins, [data]
    return ins, [None]


@shape_rule("BatchNorm")
def _bn_shape(params, ins):
    ins, outs = _chan_param_shape(params, ins, 4)
    data = ins[0]
    if data is not None:
        axis = int(params.get("axis", 1))
        c = (data[axis % len(data)],)
        return ins, [data, c, c, c, c]
    return ins, [None] * 5


@shape_rule("LayerNorm")
def _ln_shape(params, ins):
    data = ins[0]
    axis = int(params.get("axis", -1))
    if data is not None:
        c = (data[axis % len(data)],)
        ins = list(ins)
        ins[1] = c
        ins[2] = c
        red = tuple(d for i, d in enumerate(data)
                    if i != axis % len(data))
        return ins, [data, red, red]
    return ins, [None] * 3


@shape_rule("InstanceNorm")
def _in_shape(params, ins):
    return _chan_param_shape(params, ins, 2)


@shape_rule("Embedding")
def _emb_shape(params, ins):
    data = ins[0]
    ins = list(ins)
    ins[1] = (int(params["input_dim"]), int(params["output_dim"]))
    if data is not None:
        return ins, [tuple(data) + (int(params["output_dim"]),)]
    return ins, [None]


@shape_rule("LeakyReLU")
def _lrelu_shape(params, ins):
    if params.get("act_type", "leaky") == "prelu":
        return _chan_param_shape(params, ins, 1)
    return ins, [ins[0]]


@shape_rule("RNN")
def _rnn_shape(params, ins):
    """Fused RNN: infers the packed parameter-vector length and state
    shapes from the (T, B, F) data shape (reference: rnn-inl.h
    GetRnnParamSize)."""
    from ..ops.rnn import rnn_param_size
    mode = params.get("mode", "lstm")
    data = ins[0]
    if data is None:
        n_out = 1
        if params.get("state_outputs", False):
            n_out += 2 if mode == "lstm" else 1
        return ins, [None] * n_out
    h = int(params.get("state_size", 0))
    layers = int(params.get("num_layers", 1))
    bidir = bool(params.get("bidirectional", False))
    dirs = 2 if bidir else 1
    t, b, f = data
    ins = list(ins)
    ins[1] = (rnn_param_size(mode, f, h, layers, bidir),)
    state_shape = (layers * dirs, b, h)
    for i in range(2, len(ins)):
        ins[i] = state_shape
    outs = [(t, b, h * dirs)]
    if params.get("state_outputs", False):
        outs.append(state_shape)
        if mode == "lstm":
            outs.append(state_shape)
    return ins, outs


_SAME_SHAPE_BIN = True


def _infer_shapes(symbol, known_var_shapes, partial=False):
    """Iteratively propagate shapes.  Returns ({(node_id, out_idx): shape},
    {var_name: shape}) or raises MXNetError when not inferable (unless
    partial)."""
    import jax

    order = symbol._topo()
    var_sh = dict(known_var_shapes)
    # seed from var attrs
    for n in order:
        if n.is_var and "__shape__" in n.attrs and n.name not in var_sh:
            var_sh[n.name] = tuple(n.attrs["__shape__"])
    node_sh = {}

    def in_shape(node, i):
        src, idx = node.inputs[i]
        if src.is_var:
            return var_sh.get(src.name)
        return node_sh.get((id(src), idx))

    def set_in_shape(node, i, shp):
        if shp is None:
            return
        src, idx = node.inputs[i]
        if src.is_var:
            prev = var_sh.get(src.name)
            if prev is not None and tuple(prev) != tuple(shp):
                raise MXNetError(
                    "inferred shape %s for %s conflicts with %s" %
                    (shp, src.name, prev))
            var_sh[src.name] = tuple(shp)

    for _ in range(3):  # a few passes for bidirectional rules
        progress = False
        for node in order:
            if node.is_var:
                continue
            key = id(node)
            ins = [in_shape(node, i) for i in range(len(node.inputs))]
            rule = _SHAPE_RULES.get(node.op.name)
            if rule is not None:
                new_ins, outs = rule(node.params, ins)
                for i, shp in enumerate(new_ins):
                    set_in_shape(node, i, shp)
                ins = new_ins
            elif all(s is not None for s in ins):
                outs = _eval_shape_op(node, ins)
            elif node.op.name.startswith(("broadcast_", "elemwise_")) and \
                    any(s is not None for s in ins):
                # bidirectional same-shape for elemwise (reference behavior)
                shp = next(s for s in ins if s is not None)
                for i in range(len(ins)):
                    set_in_shape(node, i, shp)
                ins = [shp] * len(ins)
                outs = _eval_shape_op(node, ins)
            else:
                outs = [None] * node.num_outputs()
            for i, o in enumerate(outs):
                if o is not None and (key, i) not in node_sh:
                    node_sh[(key, i)] = tuple(o)
                    progress = True
        if not progress:
            break

    if not partial:
        missing = [n.name for n in order if n.is_var and
                   n.name not in var_sh]
        if missing:
            raise MXNetError("cannot infer shapes for arguments: %s "
                             "(provide them to infer_shape/simple_bind)" %
                             missing)
    return node_sh, var_sh


def _eval_shape_op(node, in_shapes):
    """Output shapes via jax.eval_shape on the op fn."""
    import jax
    import jax.numpy as jnp

    specs = [jax.ShapeDtypeStruct(tuple(s), jnp.float32)
             for s in in_shapes]
    params = node.params

    def call(*arrs):
        if node.op.needs_rng:
            key = jax.random.PRNGKey(0)
            out = node.op.fn(key, *arrs, **params)
        else:
            out = node.op.fn(*arrs, **params)
        return out

    try:
        out = jax.eval_shape(call, *specs)
    except Exception as e:
        # unknown shape, not an error (partial inference fills it in
        # later) — but log why, so op bugs don't hide behind "None"
        import logging
        logging.getLogger(__name__).debug(
            "eval_shape failed for op '%s' with input shapes %s "
            "(%s: %s)", node.op.name, list(in_shapes),
            type(e).__name__, e)
        return [None] * node.num_outputs()
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return [tuple(o.shape) for o in out]
