"""Image decode + augmentation pipeline (host side).

Reference capability: `python/mxnet/image/image.py` (imdecode/ImageIter/
augmenters) and `src/io/image_aug_default.cc` (the default augmenter
set).  TPU-first design note: decode and augmentation are *host* work —
they run in numpy/OpenCV on CPU threads (cv2 releases the GIL) so the
device only ever sees ready, batched tensors.  Augmented arrays are HWC
uint8/float32 numpy until batching; the device copy happens once per
batch.
"""

from __future__ import annotations

import logging
import os
import random as pyrandom

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..io.io import DataIter, DataBatch, DataDesc

try:
    import cv2 as _cv2
except ImportError:  # pragma: no cover
    _cv2 = None

_INTERP = {0: 0, 1: 1, 2: 2, 3: 3, 4: 4}  # cv2 interp enums match ids


def _require_cv2():
    if _cv2 is None:
        raise MXNetError("OpenCV (cv2) is required for mx.image")


def _jpeg_dims(buf):
    """(height, width) from a JPEG SOF marker without decoding, or None.
    Lets the decoder pick a reduced-scale IDCT when the target size is
    much smaller than the stored image (the hot-path trick the
    reference gets from libjpeg scale_denom)."""
    if len(buf) < 4 or buf[0] != 0xFF or buf[1] != 0xD8:
        return None
    i = 2
    n = len(buf)
    while i + 9 < n:
        if buf[i] != 0xFF:
            i += 1
            continue
        marker = buf[i + 1]
        if marker == 0xFF:      # fill byte (B.1.1.2): resync on next FF
            i += 1
            continue
        if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
            return (buf[i + 5] << 8 | buf[i + 6],
                    buf[i + 7] << 8 | buf[i + 8])
        if marker == 0xDA:      # SOS: entropy data follows; SOF is
            return None         # always before it, so give up
        if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
            i += 2
            continue
        i += 2 + (buf[i + 2] << 8 | buf[i + 3])
    return None


def imdecode(buf, flag=1, to_rgb=True, approx_size=0):
    """Decode an encoded image buffer to an HWC uint8 numpy array
    (reference: image.py imdecode over src/io/image_io.cc).

    ``approx_size``: smallest output side the caller will resize to; a
    JPEG at >=2x that size decodes at reduced scale (libjpeg's
    scale_denom via IMREAD_REDUCED_COLOR_*), cutting decode cost up to
    ~4x while staying above the resample target's resolution."""
    _require_cv2()
    if not isinstance(buf, (bytes, bytearray)):
        buf = bytes(buf)
    dec_flag = int(flag)
    if approx_size and flag == 1:
        dims = _jpeg_dims(buf)
        if dims:
            ratio = min(dims) // max(int(approx_size), 1)
            # REDUCED_k divides each side by k; require the reduced
            # image to still be >= approx_size so the resample only
            # ever downscales
            if ratio >= 8:
                dec_flag = _cv2.IMREAD_REDUCED_COLOR_8
            elif ratio >= 4:
                dec_flag = _cv2.IMREAD_REDUCED_COLOR_4
            elif ratio >= 2:
                dec_flag = _cv2.IMREAD_REDUCED_COLOR_2
    arr = _np.frombuffer(buf, dtype=_np.uint8)
    img = _cv2.imdecode(arr, dec_flag)
    if img is None:
        raise MXNetError("imdecode failed (truncated or unsupported "
                         "image)")
    if to_rgb and img.ndim == 3:
        img = _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    _require_cv2()
    return _cv2.resize(src, (int(w), int(h)),
                       interpolation=_INTERP.get(interp, 1))


def scale_down(src_size, size):
    """Scale (w, h) down to fit within src_size keeping aspect."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals *size* (the ImageNet eval
    transform)."""
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop with area and aspect-ratio jitter (inception-style
    training crop; reference: image.py random_size_crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * new_ratio) ** 0.5))
        new_h = int(round((target_area / new_ratio) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src.astype(_np.float32) - mean
    if std is not None:
        src /= std
    return src


# --------------------------------------------------------------------------
# Augmenters (reference: image.py Augmenter classes +
# src/io/image_aug_default.cc defaults)
# --------------------------------------------------------------------------

class Augmenter:
    """Image augmenter base: callable numpy HWC -> numpy HWC."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src.astype(_np.float32) * alpha


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        src = src.astype(_np.float32)
        gray = (src * self._coef).sum() * (3.0 / src.size)
        return src * alpha + gray * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        src = src.astype(_np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference: image.py HueJitterAug)."""

    _tyiq = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], _np.float32)
    _ityiq = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], _np.float32)
        t = _np.dot(_np.dot(self._ityiq, bt), self._tyiq).T
        return _np.dot(src.astype(_np.float32), t)


class ColorJitterAug(SequentialAug):
    """Brightness/contrast/saturation jitter in random order — the order
    is reshuffled per image (reference: RandomOrderAug)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)

    def __call__(self, src):
        order = list(self.ts)
        pyrandom.shuffle(order)
        for t in order:
            src = t(src)
        return src


class LightingAug(Augmenter):
    """PCA-noise lighting (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return src.astype(_np.float32) + rgb


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else _np.asarray(mean,
                                                          _np.float32)
        self.std = None if std is None else _np.asarray(std, _np.float32)

    def __call__(self, src):
        return color_normalize(src, 0.0 if self.mean is None
                               else self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = _np.array([[0.299], [0.587], [0.114]], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = _np.broadcast_to(
                _np.dot(src.astype(_np.float32), self._coef),
                src.shape).copy()
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False,
                    rand_resize=False, rand_mirror=False, mean=None,
                    std=None, brightness=0, contrast=0, saturation=0,
                    hue=0, pca_noise=0, rand_gray=0, inter_method=2):
    """Build the default augmenter list (reference: image.py
    CreateAugmenter / image_aug_default.cc defaults).  data_shape is CHW."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# --------------------------------------------------------------------------
# ImageIter — python-side record/list image iterator
# --------------------------------------------------------------------------

class ImageIter(DataIter):
    """Image iterator over .rec files or image lists with augmenters
    (reference: image.py ImageIter).  Decode + augment run on a thread
    pool (cv2 releases the GIL), the assembled NCHW batch is handed to
    the device in one copy."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label",
                 num_threads=None, **kwargs):
        super().__init__(batch_size)
        from ..recordio import MXIndexedRecordIO, MXRecordIO
        self.data_shape = tuple(data_shape)
        # reduced-decode hint: the first resize an augmenter applies (or
        # the output side) bounds how much resolution decode must keep.
        # Area-fraction crops (RandomSizedCropAug) sample a SUB-window
        # that is later upscaled to `size`, so they need the source kept
        # at size/sqrt(min_area) to preserve the reference's detail.
        import math
        sizes = [min(self.data_shape[1:])] if \
            len(self.data_shape) == 3 else []
        for a in (aug_list or []):
            s = getattr(a, "size", None)
            if s is None:
                continue
            side = min(int(v) for v in s) if isinstance(s, (tuple, list)) \
                else int(s)
            area = getattr(a, "area", None)
            if area is not None:
                min_area = area[0] if isinstance(area, (tuple, list)) \
                    else area
                side = int(math.ceil(side / math.sqrt(max(
                    float(min_area), 1e-6))))
            sizes.append(side)
        self._decode_hint = max(sizes) if sizes else 0
        self.label_width = label_width
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        self._rec = None
        self._list = None
        if path_imgrec:
            idx_path = kwargs.get("path_imgidx")
            if not idx_path:
                # auto-discover the .idx next to the .rec (the reference's
                # iterator requires it only for shuffle; so do we)
                guess = os.path.splitext(path_imgrec)[0] + ".idx"
                if os.path.exists(guess):
                    idx_path = guess
            if idx_path and os.path.exists(idx_path):
                self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self._keys = list(self._rec.keys)
            else:
                if shuffle:
                    raise MXNetError(
                        "shuffle=True needs an index file; pass "
                        "path_imgidx or create one with tools/im2rec.py")
                self._rec = MXRecordIO(path_imgrec, "r")
                self._keys = None
        elif path_imglist or imglist is not None:
            entries = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = _np.array(
                            [float(x) for x in parts[1:-1]], _np.float32)
                        entries.append((parts[-1], label))
            else:
                for item in imglist:
                    label = _np.asarray(item[0], _np.float32).reshape(-1)
                    entries.append((item[1], label))
            self._list = entries
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")
        self.path_root = path_root
        self.aug_list = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self._n_threads = num_threads or min(8, os.cpu_count() or 1)
        self._pool = None
        # native fast path: decode+resize+crop+mirror in the C++
        # libjpeg team (io/native_decode.py).  Only engaged when the
        # caller passes the pipeline spec (ImageRecordIter does for
        # plain classification configs) AND the library is built.
        self._native_cfg = None
        self._native_pool = None
        native_pipeline = kwargs.get("native_pipeline")
        if native_pipeline is not None:
            from ..io.native_decode import available as _native_ok
            if _native_ok():
                self._native_cfg = dict(native_pipeline)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self._cursor = 0
        if self._rec is not None and self._keys is None:
            self._rec.reset()
        if self.shuffle:
            if self._keys is not None:
                pyrandom.shuffle(self._keys)
            elif self._list is not None:
                pyrandom.shuffle(self._list)

    def _read_raw(self):
        """Next (label, encoded-or-path) pair, or None at end."""
        from ..recordio import unpack
        if self._rec is not None:
            if self._keys is not None:
                if self._cursor >= len(self._keys):
                    return None
                s = self._rec.read_idx(self._keys[self._cursor])
                self._cursor += 1
            else:
                s = self._rec.read()
                if s is None:
                    return None
            header, img = unpack(s)
            label = header.label
            return _np.atleast_1d(_np.asarray(label, _np.float32)), img
        if self._cursor >= len(self._list):
            return None
        path, label = self._list[self._cursor]
        self._cursor += 1
        with open(os.path.join(self.path_root, path), "rb") as f:
            return label, f.read()

    def _decode_augment(self, raw):
        label, buf = raw
        img = imdecode(buf, approx_size=self._decode_hint)
        for aug in self.aug_list:
            img = aug(img)
        # HWC -> CHW
        return label, _np.ascontiguousarray(
            _np.transpose(img, (2, 0, 1)).astype(_np.float32))

    def _ensure_native(self):
        """Build the C++ decode team lazily (first batch)."""
        if self._native_pool is None:
            from ..io.native_decode import NativeDecodePool
            cfg = self._native_cfg
            self._native_pool = NativeDecodePool(
                self._n_threads, self.data_shape[1:],
                resize=cfg.get("resize", 0),
                rand_crop=cfg.get("rand_crop", False),
                rand_mirror=cfg.get("rand_mirror", False))
        return self._native_pool

    def _next_native(self, raws, pad):
        """Batch path through the libjpeg worker team
        (src/io/jpeg_decode_pool.cc): decode + resize + crop + mirror
        run in C++ threads; mean/std normalization is one vectorized
        numpy pass over the assembled batch.  Returns None when any
        record is not a decodable JPEG — the caller re-runs the batch
        through the cv2 chain, which also handles PNG-packed records."""
        cfg = self._native_cfg
        bufs = [bytes(buf) for _, buf in raws]
        if not all(b[:2] == b"\xff\xd8" for b in bufs):
            return None
        out, ok = self._ensure_native().decode_batch(bufs)
        if not ok.all():
            return None
        data = out.astype(_np.float32)
        mean, std = cfg.get("mean"), cfg.get("std")
        if mean is not None:
            data -= mean
        if std is not None:
            data /= std
        data = _np.ascontiguousarray(data.transpose(0, 3, 1, 2))
        if pad:
            data = _np.concatenate(
                [data, _np.zeros((pad,) + data.shape[1:],
                                 _np.float32)])
        labels = _np.zeros(
            (self.batch_size, self.label_width), _np.float32)
        for i, (label, _) in enumerate(raws):
            labels[i, :len(label)] = label[:self.label_width]
        if self.label_width == 1:
            labels = labels[:, 0]
        return DataBatch(data=[nd.array(data)],
                         label=[nd.array(labels)], pad=pad)

    def next(self):
        import concurrent.futures as cf
        raws = []
        while len(raws) < self.batch_size:
            raw = self._read_raw()
            if raw is None:
                break
            raws.append(raw)
        if not raws:
            raise StopIteration
        pad = self.batch_size - len(raws)
        if self._native_cfg is not None:
            batch = self._next_native(raws, pad)
            if batch is not None:
                return batch
            # non-JPEG or corrupt record: cv2 chain handles the batch
        if self._pool is None:
            self._pool = cf.ThreadPoolExecutor(self._n_threads)
        decoded = list(self._pool.map(self._decode_augment, raws))
        data = _np.zeros((self.batch_size,) + self.data_shape,
                         _np.float32)
        labels = _np.zeros(
            (self.batch_size, self.label_width), _np.float32)
        for i, (label, img) in enumerate(decoded):
            if img.shape != self.data_shape:
                raise MXNetError(
                    "augmented image shape %s != data_shape %s"
                    % (img.shape, self.data_shape))
            data[i] = img
            labels[i, :len(label)] = label[:self.label_width]
        if self.label_width == 1:
            labels = labels[:, 0]
        return DataBatch(data=[nd.array(data)],
                         label=[nd.array(labels)], pad=pad)
