"""mx.image — host-side image decode + augmentation
(reference capability: python/mxnet/image/, 2,321 LoC)."""

from .image import (imdecode, imread, imresize, resize_short,  # noqa
                    fixed_crop, center_crop, random_crop,
                    random_size_crop, color_normalize, scale_down,
                    Augmenter, SequentialAug, ResizeAug, ForceResizeAug,
                    RandomCropAug, RandomSizedCropAug, CenterCropAug,
                    BrightnessJitterAug, ContrastJitterAug,
                    SaturationJitterAug, HueJitterAug, ColorJitterAug,
                    LightingAug, ColorNormalizeAug, RandomGrayAug,
                    HorizontalFlipAug, CastAug, CreateAugmenter,
                    ImageIter)
from .detection import (DetAugmenter, DetBorrowAug,  # noqa
                        DetRandomSelectAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug,
                        CreateDetAugmenter, ImageDetIter)
