"""Detection-aware image augmenters + ImageDetIter.

Reference: ``python/mxnet/image/detection.py`` and the C++ augmenter
``src/io/image_det_aug_default.cc`` (686 LoC) — geometric augmentations
keep the bbox labels consistent with the pixels.

Label format (the reference's "detection list" layout): per image, a
flat float vector ``[header_width, object_width, extra..., obj0...,
obj1...]`` where each object is ``[class_id, xmin, ymin, xmax, ymax]``
with coordinates normalized to [0, 1]; batches pad objects with
class_id = -1 rows.
"""

from __future__ import annotations

import random as pyrandom

import numpy as _np

from .image import (Augmenter, ImageIter, fixed_crop, imresize)
from ..io.io import DataBatch, DataDesc
from ..base import MXNetError
from .. import ndarray as nd

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)
    (reference: detection.py DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image Augmenter that does not change geometry
    (color jitter, normalize, cast — reference: DetBorrowAug)."""

    def __init__(self, augmenter):
        assert isinstance(augmenter, Augmenter)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly select one of the given augmenters (or skip)
    (reference: DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror the x coordinates
    (reference: DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            x2 = label[valid, 3].copy()
            label[valid, 1] = 1.0 - x2
            label[valid, 3] = 1.0 - x1
        return src, label


def _box_iob(boxes, crop):
    """Intersection-over-box-area of each box with the crop window."""
    x1 = _np.maximum(boxes[:, 0], crop[0])
    y1 = _np.maximum(boxes[:, 1], crop[1])
    x2 = _np.minimum(boxes[:, 2], crop[2])
    y2 = _np.minimum(boxes[:, 3], crop[3])
    inter = _np.maximum(x2 - x1, 0) * _np.maximum(y2 - y1, 0)
    area = _np.maximum((boxes[:, 2] - boxes[:, 0]) *
                       (boxes[:, 3] - boxes[:, 1]), 1e-12)
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by object coverage (reference:
    DetRandomCropAug / image_det_aug_default.cc RandomCrop): sample a
    crop whose IoB with at least one object exceeds min_object_covered;
    objects whose remaining coverage is below min_eject_coverage are
    dropped; surviving boxes are clipped and renormalized."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            w = min((area * ratio) ** 0.5, 1.0)
            h = min((area / ratio) ** 0.5, 1.0)
            x0 = pyrandom.uniform(0, 1 - w)
            y0 = pyrandom.uniform(0, 1 - h)
            crop = (x0, y0, x0 + w, y0 + h)
            valid = label[:, 0] >= 0
            if not valid.any():
                return crop
            cov = _box_iob(label[valid, 1:5], crop)
            if cov.max() >= self.min_object_covered:
                return crop
        return None

    def __call__(self, src, label):
        crop = self._sample_crop(label)
        if crop is None:
            return src, label
        h, w = src.shape[0], src.shape[1]
        x0, y0, x1, y1 = crop
        xi, yi = int(x0 * w), int(y0 * h)
        wi = max(int((x1 - x0) * w), 1)
        hi = max(int((y1 - y0) * h), 1)
        src = fixed_crop(src, xi, yi, wi, hi)
        out = label.copy()
        valid = out[:, 0] >= 0
        boxes = out[valid, 1:5]
        cov = _box_iob(boxes, crop)
        cw = x1 - x0
        ch = y1 - y0
        nb = _np.empty_like(boxes)
        nb[:, 0] = _np.clip((boxes[:, 0] - x0) / cw, 0, 1)
        nb[:, 1] = _np.clip((boxes[:, 1] - y0) / ch, 0, 1)
        nb[:, 2] = _np.clip((boxes[:, 2] - x0) / cw, 0, 1)
        nb[:, 3] = _np.clip((boxes[:, 3] - y0) / ch, 0, 1)
        keep = cov >= self.min_eject_coverage
        ids = _np.where(valid)[0]
        out[ids, 1:5] = nb
        out[ids[~keep], 0] = -1          # ejected objects become padding
        return src, out


class DetRandomPadAug(DetAugmenter):
    """Pad to a larger canvas (zoom out) and rescale boxes
    (reference: DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.pad_val = pad_val

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        area = pyrandom.uniform(*self.area_range)
        if area <= 1.0:
            return src, label
        ratio = pyrandom.uniform(*self.aspect_ratio_range)
        nw = int(w * (area * ratio) ** 0.5)
        nh = int(h * (area / ratio) ** 0.5)
        nw, nh = max(nw, w), max(nh, h)
        x0 = pyrandom.randint(0, nw - w)
        y0 = pyrandom.randint(0, nh - h)
        canvas = _np.empty((nh, nw, src.shape[2]), src.dtype)
        canvas[:] = _np.asarray(self.pad_val, src.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = src
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * w + x0) / nw
        out[valid, 3] = (out[valid, 3] * w + x0) / nw
        out[valid, 2] = (out[valid, 2] * h + y0) / nh
        out[valid, 4] = (out[valid, 4] * h + y0) / nh
        return canvas, out


class _DetResizeAug(DetAugmenter):
    """Force resize (boxes are normalized, so labels are unchanged)."""

    def __init__(self, size, interp=2):
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter list (reference:
    detection.py CreateDetAugmenter)."""
    from .image import (BrightnessJitterAug, ContrastJitterAug,
                        SaturationJitterAug, HueJitterAug, LightingAug,
                        ColorNormalizeAug, CastAug)
    auglist = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(area_range[1], 1.0)),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(_DetResizeAug((data_shape[2], data_shape[1]),
                                 inter_method))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        if brightness:
            auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
        if contrast:
            auglist.append(DetBorrowAug(ContrastJitterAug(contrast)))
        if saturation:
            auglist.append(DetBorrowAug(SaturationJitterAug(saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval,
                                                eigvec)))
    # same semantics as image.py CreateAugmenter: True -> ImageNet
    # constants; None -> skip that component
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    auglist.append(DetBorrowAug(CastAug()))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: ImageIter with detection labels + detection
    augmenters (reference: detection.py ImageDetIter).

    Raw labels may be either the header format
    [header_width, object_width, extra..., objects...] or a flat
    [id, x1, y1, x2, y2] * N vector.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="label", label_shape=None, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        super().__init__(batch_size, data_shape, label_width=-1,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        self.det_aug_list = aug_list
        if label_shape is not None:
            # (max_objects, 5) given explicitly (reference: ImageDetIter
            # label_shape) — skips the dataset scan
            self._max_objects = int(label_shape[0])
        elif self._list is not None:
            # labels are already in memory; no image I/O needed
            self._max_objects = max(
                (self._parse_label(lab).shape[0]
                 for _, lab in self._list), default=1)
        else:
            self._max_objects = self._scan_max_objects()

    @staticmethod
    def _parse_label(raw):
        """Raw flat vector -> (n_obj, 5) [id, x1, y1, x2, y2].

        Header form requires an INTEGRAL header width >= 2 and object
        width >= 5 that exactly tile the remainder — otherwise the
        vector is treated as flat [id, x1, y1, x2, y2] * N (a flat
        label whose first class id happens to be >= 2 must not be
        mistaken for a header)."""
        raw = _np.asarray(raw, _np.float32).ravel()
        # flat first: a size divisible by 5 can never be the common
        # header=2/obj_w=5 layout (2 + 5n is never a multiple of 5)
        if raw.size % 5 == 0 and raw.size > 0:
            return raw.reshape(-1, 5).astype(_np.float32)
        if raw.size >= 2:
            header, obj_w = float(raw[0]), float(raw[1])
            if (header.is_integer() and obj_w.is_integer() and
                    header >= 2 and obj_w >= 5 and raw.size > header and
                    (raw.size - int(header)) % int(obj_w) == 0):
                body = raw[int(header):]
                n = body.size // int(obj_w)
                return body[:n * int(obj_w)].reshape(n, int(obj_w))[:, :5] \
                    .astype(_np.float32)
        raise MXNetError(
            "label length %d is not a multiple of 5 and has no valid "
            "header" % raw.size)

    def _scan_max_objects(self):
        self.reset()
        mx_obj = 1
        while True:
            raw = self._read_raw()
            if raw is None:
                break
            mx_obj = max(mx_obj, self._parse_label(raw[0]).shape[0])
        self.reset()
        return mx_obj

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self._max_objects, 5))]

    def next(self):
        from .image import imdecode
        data = _np.zeros((self.batch_size,) + self.data_shape,
                         _np.float32)
        labels = _np.full((self.batch_size, self._max_objects, 5),
                          -1.0, _np.float32)
        n = 0
        while n < self.batch_size:
            raw = self._read_raw()
            if raw is None:
                break
            lab, buf = raw
            img = imdecode(buf) if isinstance(buf, (bytes, bytearray)) \
                else buf
            objs = self._parse_label(lab)
            padded = _np.full((self._max_objects, 5), -1.0, _np.float32)
            padded[:objs.shape[0]] = objs[:self._max_objects]
            for aug in self.det_aug_list:
                img, padded = aug(img, padded)
            arr = _np.asarray(img, _np.float32)
            if arr.shape[:2] != (self.data_shape[1], self.data_shape[2]):
                arr = _np.asarray(imresize(arr, self.data_shape[2],
                                           self.data_shape[1]),
                                  _np.float32)
            data[n] = arr.transpose(2, 0, 1)
            labels[n] = padded
            n += 1
        if n == 0:
            raise StopIteration
        return DataBatch(data=[nd.array(data)], label=[nd.array(labels)],
                         pad=self.batch_size - n,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
