"""Symbolic RNN cells.

Reference: ``python/mxnet/rnn/rnn_cell.py`` — cells build unrolled
symbol graphs for the BucketingModule workflow (per-sequence-length
executors sharing one parameter set).

TPU-native note: an unrolled bucket compiles to ONE XLA program per
sequence length; the per-bucket executable cache in BucketingModule is
the recompile-storm mitigation (SURVEY.md §7 hard part (e)).  The
``FusedRNNCell`` lowers to the single fused RNN op (lax.scan inside) and
is the preferred form for long sequences.
"""

from __future__ import annotations

from .. import symbol as sym


class RNNParams:
    """Container reusing weight symbols across time steps
    (reference: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.var(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell (reference: rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def prefix(self):
        return self._prefix

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [s["shape"] for s in self.state_info]

    @property
    def _gate_names(self):
        return ("",)

    def begin_state(self, func=None, **kwargs):
        """Initial-state symbols (reference: BaseRNNCell.begin_state).

        With no *func*, returns ``None`` and :meth:`unroll` builds
        zero states from the input symbol (shape inference here has no
        "0 = unknown batch" convention, so standalone zeros symbols
        cannot be created without the batch size — pass
        ``func=sym.zeros, batch_size=N`` for explicit states)."""
        if func is None:
            return None
        states = []
        batch = kwargs.pop("batch_size", 0)
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            shape = tuple(batch if s == 0 else s for s in info["shape"])
            states.append(func(name=name, shape=shape, **kwargs))
        return states

    def _zero_state_from(self, ref):
        """Zero initial states derived from a per-step input symbol
        ``ref`` of shape (batch, feat): (batch, 1) zeros tiled to each
        state's trailing dims."""
        z1 = sym.sum(ref * 0.0, axis=-1, keepdims=True)  # (batch, 1)
        states = []
        for info in self.state_info:
            shape = info["shape"]
            if len(shape) == 2:       # (batch, H)
                states.append(sym.tile(z1, reps=(1, shape[1])))
            else:                     # (L, batch, H) fused layout
                z = sym.expand_dims(z1, axis=0)       # (1, batch, 1)
                states.append(sym.tile(z, reps=(shape[0], 1, shape[2])))
        return states

    def __call__(self, inputs, states):
        raise NotImplementedError

    def unroll(self, length, inputs=None, begin_state=None,
               layout="NTC", merge_outputs=None, input_prefix=""):
        """Unroll over *length* steps (reference: BaseRNNCell.unroll).

        inputs: a single (batch, seq, feat) symbol (layout NTC), a
        (seq, batch, feat) symbol (TNC), or a list of per-step symbols.
        Returns (outputs, states): outputs is a list of per-step symbols
        or one merged symbol when merge_outputs=True.
        """
        self.reset()
        if inputs is None:
            inputs = [sym.var("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, sym.Symbol):
            axis = 1 if layout == "NTC" else 0
            inputs = list(sym.split(inputs, num_outputs=length,
                                    axis=axis, squeeze_axis=True))
        assert len(inputs) == length
        states = begin_state if begin_state is not None else \
            self._zero_state_from(inputs[0])
        outputs = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
        if merge_outputs:
            axis = 1 if layout == "NTC" else 0
            merged = [sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = sym.concat(*merged, dim=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Elman RNN: h' = act(W x + b_i + U h + b_h)
    (reference: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM (reference: rnn_cell.py LSTMCell; gate order i f c o matches
    the fused op's packed layout)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=4 * self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=4 * self._num_hidden,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        slices = list(sym.SliceChannel(gates, num_outputs=4,
                                       name="%sslice" % name))
        in_gate = sym.Activation(slices[0], act_type="sigmoid")
        forget_gate = sym.Activation(slices[1] + self._forget_bias,
                                     act_type="sigmoid")
        in_trans = sym.Activation(slices[2], act_type="tanh")
        out_gate = sym.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU (reference: rnn_cell.py GRUCell; gate order r z n)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW,
                                 bias=self._iB,
                                 num_hidden=3 * self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=3 * self._num_hidden,
                                 name="%sh2h" % name)
        i_r, i_z, i_n = list(sym.SliceChannel(i2h, num_outputs=3))
        h_r, h_z, h_n = list(sym.SliceChannel(h2h, num_outputs=3))
        reset = sym.Activation(i_r + h_r, act_type="sigmoid")
        update = sym.Activation(i_z + h_z, act_type="sigmoid")
        newmem = sym.Activation(i_n + reset * h_n, act_type="tanh")
        next_h = update * states[0] + (1.0 - update) * newmem
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Wraps the single fused RNN op (lax.scan kernel) — the fast path
    for full-sequence unrolls (reference: rnn_cell.py FusedRNNCell over
    src/operator/rnn-inl.h)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, prefix=None,
                 params=None):
        prefix = prefix if prefix is not None else "%s_" % mode
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._param = self.params.get("parameters")

    @property
    def state_info(self):
        b = 2 if self._bidirectional else 1
        info = [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (b * self._num_layers, 0,
                                   self._num_hidden),
                         "__layout__": "LNC"})
        return info

    def unroll(self, length, inputs=None, begin_state=None,
               layout="NTC", merge_outputs=None, input_prefix=""):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            inputs = sym.concat(*[sym.expand_dims(i, axis=0)
                                  for i in inputs], dim=0)  # TNC
        elif layout == "NTC":
            inputs = sym.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None:
            # (T, B, F) -> (B, F) reference row for zero-state shapes
            ref = sym.sum(inputs * 0.0, axis=0)
            begin_state = self._zero_state_from(ref)
        states = list(begin_state)
        kwargs = {"state_size": self._num_hidden,
                  "num_layers": self._num_layers,
                  "mode": self._mode,
                  "bidirectional": self._bidirectional,
                  "p": self._dropout,
                  "state_outputs": True}
        if self._mode == "lstm":
            out = sym.RNN(inputs, self._param, states[0], states[1],
                          name="%srnn" % self._prefix, **kwargs)
            outputs, s0, s1 = out[0], out[1], out[2]
            nstates = [s0, s1]
        else:
            out = sym.RNN(inputs, self._param, states[0],
                          name="%srnn" % self._prefix, **kwargs)
            outputs, s0 = out[0], out[1]
            nstates = [s0]
        if layout == "NTC":
            outputs = sym.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            axis = 1 if layout == "NTC" else 0
            outputs = list(sym.split(outputs, num_outputs=length,
                                     axis=axis, squeeze_axis=True))
        return outputs, nstates


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in sequence (reference:
    rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__("", params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        return self

    @property
    def state_info(self):
        out = []
        for c in self._cells:
            out.extend(c.state_info)
        return out

    def begin_state(self, func=None, **kwargs):
        if func is None:
            return None
        out = []
        for c in self._cells:
            out.extend(c.begin_state(func=func, **kwargs))
        return out

    def reset(self):
        super().reset()
        for c in self._cells:
            c.reset()

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        for c in self._cells:
            n = len(c.state_info)
            inputs, st = c(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(st)
        return inputs, next_states

    def unroll(self, length, inputs=None, begin_state=None,
               layout="NTC", merge_outputs=None, input_prefix=""):
        self.reset()
        pos = 0
        next_states = []
        outputs = inputs
        for i, c in enumerate(self._cells):
            n = len(c.state_info)
            bs = begin_state[pos:pos + n] if begin_state is not None \
                else None
            outputs, st = c.unroll(
                length, inputs=outputs, begin_state=bs,
                layout=layout,
                merge_outputs=(merge_outputs
                               if i == len(self._cells) - 1 else None),
                input_prefix=input_prefix)
            pos += n
            next_states.extend(st)
        return outputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence and
    concatenates outputs (reference: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._l = l_cell
        self._r = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def begin_state(self, func=None, **kwargs):
        if func is None:
            return None
        return self._l.begin_state(func=func, **kwargs) + \
            self._r.begin_state(func=func, **kwargs)

    def reset(self):
        super().reset()
        self._l.reset()
        self._r.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs=None, begin_state=None,
               layout="NTC", merge_outputs=None, input_prefix=""):
        self.reset()
        if inputs is None:
            inputs = [sym.var("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, sym.Symbol):
            axis = 1 if layout == "NTC" else 0
            inputs = list(sym.split(inputs, num_outputs=length,
                                    axis=axis, squeeze_axis=True))
        nl = len(self._l.state_info)
        l_bs = begin_state[:nl] if begin_state is not None else None
        r_bs = begin_state[nl:] if begin_state is not None else None
        l_out, l_states = self._l.unroll(
            length, inputs=inputs, begin_state=l_bs, layout=layout,
            merge_outputs=False)
        r_out, r_states = self._r.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=r_bs, layout=layout, merge_outputs=False)
        outputs = [sym.concat(l, r, dim=1,
                              name="%st%d" % (self._output_prefix, i))
                   for i, (l, r) in enumerate(
                       zip(l_out, reversed(r_out)))]
        if merge_outputs:
            axis = 1 if layout == "NTC" else 0
            outputs = sym.concat(*[sym.expand_dims(o, axis=axis)
                                   for o in outputs], dim=axis)
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell
    (reference: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__("", None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)

    def reset(self):
        super().reset()
        self.base_cell.reset()


class DropoutCell(ModifierCell):
    """Applies dropout on the base cell's output
    (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, base_cell, dropout=0.5):
        super().__init__(base_cell)
        self._dropout = dropout

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        if self._dropout > 0:
            out = sym.Dropout(out, p=self._dropout)
        return out, states


class ResidualCell(ModifierCell):
    """Adds the input to the base cell's output
    (reference: rnn_cell.py ResidualCell)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states
