"""Bucketing data iterator for variable-length sequences.

Reference: ``python/mxnet/rnn/io.py`` BucketSentenceIter — assigns each
sentence to the smallest bucket that fits, pads to the bucket length,
and emits batches tagged with ``bucket_key`` so BucketingModule can pick
the matching per-length executor.
"""

from __future__ import annotations

import random as _random

import numpy as _np

from ..io.io import DataBatch, DataDesc, DataIter
from .. import ndarray as nd

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """(reference: rnn/io.py BucketSentenceIter)

    sentences: list of lists of int token ids.  Labels are the inputs
    shifted by one (next-token prediction), padded with invalid_label.
    """

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT", shuffle=True, seed=0):
        if layout != "NT":
            raise ValueError(
                "only layout='NT' (batch-major) is implemented; got %r"
                % (layout,))
        if buckets is None:
            lens = _np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)
        self.buckets = buckets
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self._dtype = dtype
        self._shuffle = shuffle
        self._rng = _random.Random(seed)

        self.data = [[] for _ in buckets]
        ndiscard = 0
        for s in sentences:
            buck = None
            for i, blen in enumerate(buckets):
                if len(s) <= blen:
                    buck = i
                    break
            if buck is None:
                ndiscard += 1
                continue
            padded = _np.full((buckets[buck],), invalid_label,
                              dtype=_np.float32)
            padded[:len(s)] = s
            self.data[buck].append(padded)
        self.data = [_np.asarray(x) if x else
                     _np.zeros((0, b)) for x, b in zip(self.data, buckets)]
        self._ndiscard = ndiscard
        if ndiscard:
            import logging
            logging.warning(
                "BucketSentenceIter: discarded %d sentences longer than "
                "the largest bucket (%d)", ndiscard, buckets[-1])

        self.default_bucket_key = max(buckets)
        super().__init__(batch_size)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self._plan = []
        for i, d in enumerate(self.data):
            n = len(d) // self.batch_size
            order = list(range(len(d)))
            if self._shuffle:
                self._rng.shuffle(order)
            for j in range(n):
                self._plan.append(
                    (i, order[j * self.batch_size:(j + 1) *
                              self.batch_size]))
        if self._shuffle:
            self._rng.shuffle(self._plan)
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        bucket, rows = self._plan[self._cursor]
        self._cursor += 1
        seqs = self.data[bucket][rows]
        label = _np.full_like(seqs, self.invalid_label)
        label[:, :-1] = seqs[:, 1:]
        blen = self.buckets[bucket]
        return DataBatch(
            data=[nd.array(seqs.astype(self._dtype))],
            label=[nd.array(label.astype(self._dtype))],
            bucket_key=blen,
            provide_data=[DataDesc(self.data_name,
                                   (self.batch_size, blen))],
            provide_label=[DataDesc(self.label_name,
                                    (self.batch_size, blen))])
