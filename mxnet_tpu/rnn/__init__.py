"""Symbolic RNN cells + bucketing I/O for BucketingModule workflows.

Reference: ``python/mxnet/rnn/`` (1,797 LoC — rnn_cell.py symbolic cells,
io.py BucketSentenceIter).
"""

from .rnn_cell import (BaseRNNCell, RNNParams, RNNCell, LSTMCell,  # noqa
                       GRUCell, FusedRNNCell, SequentialRNNCell,
                       BidirectionalCell, DropoutCell, ResidualCell,
                       ModifierCell)
from .io import BucketSentenceIter  # noqa: F401
