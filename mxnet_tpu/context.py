"""Device context management.

TPU-native equivalent of the reference's ``Context``
(``/root/reference/python/mxnet/context.py``): a lightweight handle naming a
device (``cpu(0)``, ``tpu(2)``) plus a thread-local "current context" stack
used by every array-creating call.  Unlike the reference, the device itself is
a live ``jax.Device`` — placement happens via ``jax.device_put`` / sharding
rather than a C++ storage manager.
"""

from __future__ import annotations

import threading

import jax

__all__ = [
    "Context", "cpu", "gpu", "tpu", "current_context", "num_tpus", "num_gpus",
]

# devtype ids mirror the reference's enum (kCPU=1, kGPU=2, kCPUPinned=3,
# reference include/mxnet/base.h); TPU takes the GPU slot's role.
_DEVTYPE2ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID2DEVTYPE = {v: k for k, v in _DEVTYPE2ID.items()}


class Context:
    """A device context.

    Parameters
    ----------
    device_type : str
        'cpu', 'tpu' or 'gpu' ('gpu' is accepted as an alias for the
        accelerator so reference scripts run unmodified).
    device_id : int
        Ordinal of the device within its platform.
    """

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type = device_type.device_type
            self.device_id = device_type.device_id
        else:
            if device_type not in _DEVTYPE2ID:
                raise ValueError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = device_id

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self):
        return _DEVTYPE2ID[self.device_type]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- jax mapping ------------------------------------------------------
    @property
    def jax_device(self):
        """The live ``jax.Device`` this context names."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = _platform_devices("cpu")
        else:
            devs = _accelerator_devices()
        if not devs:
            raise RuntimeError("no %s devices visible to JAX" % self.device_type)
        return devs[self.device_id % len(devs)]

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()

    def empty_cache(self):
        """Release cached device memory (best-effort; XLA owns HBM)."""
        # XLA manages HBM with its own allocator; nothing to do but keep the
        # reference API (ndarray.py Context.empty_cache) available.
        return None


def _platform_devices(platform):
    """THIS process's devices for a platform.  Under jax.distributed
    (multi-host) ``jax.devices()`` is the global list including peers'
    non-addressable devices; a Context must always name a local one."""
    try:
        return jax.local_devices(backend=platform)
    except RuntimeError:
        return []


def _accelerator_devices():
    """This process's devices of the default (non-cpu) platform, else cpu."""
    devs = jax.local_devices()
    non_cpu = [d for d in devs if d.platform != "cpu"]
    return non_cpu if non_cpu else devs


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def tpu(device_id=0):
    """Return a TPU context (the accelerator platform JAX sees)."""
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Alias of :func:`tpu` so reference scripts using ``mx.gpu()`` run."""
    return Context("tpu", device_id)


def num_tpus():
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return len(devs)


def num_gpus():
    return num_tpus()


def current_context():
    """The context on top of the ``with ctx:`` stack (default: accelerator
    if present, else cpu — unlike the reference which defaults to cpu, a TPU
    framework defaults to the chip)."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return Context._default_ctx_value()


def _default_ctx_value():
    if num_tpus() > 0:
        return Context("tpu", 0)
    return Context("cpu", 0)


Context._default_ctx_value = staticmethod(_default_ctx_value)
