"""KVStore (reference: python/mxnet/kvstore.py over src/kvstore/).

Implemented in the parallel milestone; see create()."""

from __future__ import annotations


def create(name="local"):
    from ._kvstore_impl import create as _create
    return _create(name)
