"""mx.kv — key-value store (reference: python/mxnet/kvstore.py over
src/kvstore/; see _kvstore_impl.py for the TPU-native backends)."""

from ._kvstore_impl import create, KVStoreBase  # noqa: F401
from ._kvstore_impl import (KVStoreLocal, KVStoreTPU, KVStoreDist,  # noqa
                            KVStoreServer)
from ._kvstore_impl import (RPCTimeoutError, SyncTimeoutError,  # noqa
                            EvictedWorkerError)
