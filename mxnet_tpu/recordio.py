"""RecordIO file format (reference: python/mxnet/recordio.py, 488 LoC, and
src/io/image_recordio.h).

Binary framing: [magic u32][lrecord u32][data][pad to 4B], where lrecord
encodes cflag (3 bits) + length (29 bits); identical layout to the
reference so .rec files interoperate.  ``IRHeader`` packs image records the
same way as ``mx.recordio.pack``.
"""

from __future__ import annotations

import collections
import os
import struct

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a


def _lrecord(cflag, length):
    return (cflag << 29) | length


def _parse_lrecord(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential .rec reader/writer (reference: recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fid = None
        self.open()

    def open(self):
        self._native = None
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            # read path goes through the native C++ reader when built
            # (src/io/recordio_reader.cc — the reference reads records
            # natively too, iter_image_recordio_2.cc); gated by
            # MXNET_USE_NATIVE_RECORDIO
            from .config import get_env
            from . import recordio_native
            if get_env("MXNET_USE_NATIVE_RECORDIO") and \
                    recordio_native.available():
                self._native = recordio_native.NativeRecordReader(self.uri)
                self.fid = None
            else:
                self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)

    def close(self):
        if self._native is not None:
            self._native.close()
            self._native = None
        if self.fid is not None:
            self.fid.close()
            self.fid = None

    def reset(self):
        self.close()
        self.open()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()

    def tell(self):
        if self._native is not None:
            return self._native.tell()
        return self.fid.tell()

    def write(self, buf):
        assert self.writable
        self.fid.write(struct.pack("<II", _MAGIC, _lrecord(0, len(buf))))
        self.fid.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._native is not None:
            return self._native.read()
        header = self.fid.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        assert magic == _MAGIC, "invalid record magic"
        _cflag, length = _parse_lrecord(lrec)
        buf = self.fid.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fid.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec with .idx file
    (reference: recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.fid is not None and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        if self._native is not None:
            self._native.seek(self.idx[idx])
            return
        self.fid.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = collections.namedtuple("IRHeader",
                                  ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a header + byte payload (reference: recordio.py pack)."""
    flag = header.flag
    label = header.label
    if isinstance(label, (list, tuple, _np.ndarray)) and \
            not _np.isscalar(label):
        label = _np.asarray(label, dtype=_np.float32)
        header = IRHeader(len(label), 0.0, header.id, header.id2)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    header = IRHeader(0, float(label), header.id, header.id2)
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Unpack bytes into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[:header.flag * 4], dtype=_np.float32)
        header = IRHeader(header.flag, label, header.id, header.id2)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array; encodes via PIL if available else raw npy."""
    try:
        from io import BytesIO
        from PIL import Image
        buf = BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(img.astype(_np.uint8)).save(buf, format=fmt,
                                                    quality=quality)
        return pack(header, buf.getvalue())
    except ImportError:
        from io import BytesIO
        buf = BytesIO()
        _np.save(buf, img)
        return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    header, payload = unpack(s)
    from io import BytesIO
    if payload[:6] == b"\x93NUMPY":
        img = _np.load(BytesIO(payload))
    else:
        from PIL import Image
        img = _np.asarray(Image.open(BytesIO(payload)))
    return header, img
