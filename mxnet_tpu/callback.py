"""Training callbacks (reference: python/mxnet/callback.py, 222 LoC:
Speedometer, do_checkpoint, log_train_metric, ProgressBar)."""

from __future__ import annotations

import logging
import math
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "ProgressBar", "module_checkpoint"]


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving prefix-symbol.json + prefix-NNNN.params
    (reference: callback.py do_checkpoint)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Throughput logger: every *frequent* batches, log samples/sec and
    the current metric values (API-compatible with the reference's
    Speedometer batch-end callback)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._window_start = None   # perf_counter at window begin
        self._prev_batch = -1

    def __call__(self, param):
        batch = param.nbatch
        if batch < self._prev_batch or self._window_start is None:
            # new epoch (batch counter reset) — restart the window
            self._window_start = time.perf_counter()
            self._prev_batch = batch
            return
        self._prev_batch = batch
        if batch == 0 or batch % self.frequent:
            return
        elapsed = time.perf_counter() - self._window_start
        rate = (self.frequent * self.batch_size / elapsed) if elapsed \
            else float("inf")
        parts = ["Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                 % (param.epoch, batch, rate)]
        metric = param.eval_metric
        if metric is not None:
            parts += ["%s=%f" % kv for kv in metric.get_name_value()]
            if self.auto_reset:
                metric.reset()
        logging.info("\t".join(parts))
        self._window_start = time.perf_counter()


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
