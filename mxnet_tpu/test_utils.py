"""Test harness — the per-op numeric oracle.

Reference capability: `python/mxnet/test_utils.py` —
`check_numeric_gradient` (:790, finite differences vs symbolic grad),
`check_symbolic_forward`/`check_symbolic_backward` (:926,:1054),
`assert_almost_equal` (:470), `rand_ndarray` (:339), and
`check_consistency` (:1207), the cross-backend oracle (cpu-vs-gpu in the
reference, cpu-vs-tpu here).  SURVEY §4.1 calls this the single most
important harness to reproduce.
"""

from __future__ import annotations

import numpy as np

from . import context as ctx_mod
from . import ndarray as nd
from .ndarray import NDArray

__all__ = [
    "default_context", "assert_almost_equal", "almost_equal", "same",
    "rand_ndarray", "rand_shape_nd", "random_arrays",
    "numeric_grad", "check_numeric_gradient",
    "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "list_backends", "tiny_attention_lm",
    "dense_decode_reference",
]

_DEFAULT_RTOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
                 np.dtype(np.float64): 1e-5}
_DEFAULT_ATOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5,
                 np.dtype(np.float64): 1e-7}


def default_context():
    return ctx_mod.current_context()


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = np.asarray(a), np.asarray(b)
    rtol = rtol if rtol is not None else \
        _DEFAULT_RTOL.get(a.dtype, 1e-4)
    atol = atol if atol is not None else \
        _DEFAULT_ATOL.get(a.dtype, 1e-5)
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b_np = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    rtol = rtol if rtol is not None else \
        _DEFAULT_RTOL.get(a_np.dtype, 1e-4)
    atol = atol if atol is not None else \
        _DEFAULT_ATOL.get(a_np.dtype, 1e-5)
    np.testing.assert_allclose(
        a_np, b_np, rtol=rtol, atol=atol, equal_nan=True,
        err_msg="%s and %s differ" % names)


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None):
    dtype = dtype or np.float32
    if stype == "default":
        return nd.array(np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    from .ndarray import sparse as _sp
    density = 0.5 if density is None else density
    arr = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.uniform(0, 1, shape[:1]) < density
    arr[~mask] = 0
    dense = nd.array(arr, ctx=ctx)
    if stype == "row_sparse":
        return dense.tostype("row_sparse")
    if stype == "csr":
        arr2 = arr * (np.random.uniform(0, 1, shape) < density)
        return nd.array(arr2).tostype("csr")
    raise ValueError("unknown stype %r" % stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def _as_location(sym, location):
    """Normalize user-provided inputs to {arg_name: numpy}."""
    args = sym.list_arguments()
    if isinstance(location, dict):
        return {k: np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
                for k, v in location.items()}
    return {name: np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
            for name, v in zip(args, location)}


def _bind(sym, location, aux_states=None, grad_req="write", ctx=None,
          dtype=None):
    ctx = ctx or default_context()
    args = {}
    grads = {}
    for name, v in location.items():
        v = np.asarray(v, dtype=dtype) if dtype else np.asarray(v)
        args[name] = nd.array(v, ctx=ctx)
        grads[name] = nd.zeros(v.shape, ctx=ctx, dtype=v.dtype)
    aux = {k: nd.array(np.asarray(v), ctx=ctx)
           for k, v in (aux_states or {}).items()}
    return sym.bind(ctx=ctx, args=args, args_grad=grads,
                    grad_req=grad_req, aux_states=aux)


def numeric_grad(f, location, eps=1e-4):
    """Central-difference gradients of scalar-valued f(dict)->float."""
    grads = {}
    for name, v in location.items():
        v = np.asarray(v, dtype=np.float64)
        g = np.zeros_like(v)
        flat = v.reshape(-1)
        gflat = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            fp = f({**location, name: v})
            flat[i] = orig - eps
            fm = f({**location, name: v})
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * eps)
        grads[name] = g
    return grads


def check_numeric_gradient(sym, location, aux_states=None, eps=1e-3,
                           rtol=1e-2, atol=1e-4, grad_nodes=None,
                           ctx=None):
    """Symbolic gradients vs central finite differences
    (reference: test_utils.py:790).

    The comparison runs in float64 — finite differences in f32 would
    drown real gradient bugs in rounding noise.
    """
    # jax removed the top-level `jax.enable_x64` alias; the supported
    # per-scope switch lives in jax.experimental
    from jax.experimental import enable_x64
    with enable_x64():
        location = _as_location(sym, location)
        location = {k: np.asarray(v, np.float64)
                    for k, v in location.items()}
        aux64 = {k: np.asarray(
                    v.asnumpy() if isinstance(v, NDArray) else v,
                    np.float64)
                 for k, v in (aux_states or {}).items()}
        grad_nodes = grad_nodes or list(location)
        exe = _bind(sym, location, aux64, ctx=ctx)
        outs = exe.forward(is_train=True)
        # random fixed projection makes the output scalar
        rs = np.random.RandomState(0)
        proj = [rs.normal(0, 1, o.shape).astype(np.float64)
                for o in outs]
        exe.backward(out_grads=[nd.array(p) for p in proj])
        sym_grads = {n: exe.grad_dict[n].asnumpy() for n in grad_nodes}

        # ONE probe executor reused across every finite-difference
        # evaluation: a fresh _bind per probe would build fresh jit
        # closures and recompile the forward program for EVERY one of
        # the 2-per-element evaluations (minutes per test, the reason
        # these suites used to be unaffordable).  Fresh-bind semantics
        # are restored by hand each call: the PRNG key rewinds to the
        # bind-time key (stochastic ops replay identical masks, so f
        # stays deterministic) and train-mode aux updates (BatchNorm
        # stats) are rolled back to the bind-time handles.
        probe = _bind(sym, location, aux64, ctx=ctx)
        key0 = probe._key
        aux0 = {n: a._data for n, a in probe.aux_dict.items()}

        def f(loc):
            probe._key = key0
            for n, a in probe.aux_dict.items():
                a._data = aux0[n]
            os = probe.forward(is_train=True, **{**location, **loc})
            return sum(float(np.sum(o.asnumpy() * p))
                       for o, p in zip(os, proj))

        num_grads = numeric_grad(
            f, {n: location[n] for n in grad_nodes}, eps=eps)
        for n in grad_nodes:
            np.testing.assert_allclose(
                sym_grads[n], num_grads[n], rtol=rtol, atol=atol,
                err_msg="numeric vs symbolic gradient mismatch for %r "
                        "of %s" % (n, sym.list_outputs()))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    """Forward outputs vs expected numpy arrays (reference: :926)."""
    location = _as_location(sym, location)
    exe = _bind(sym, location, aux_states, ctx=ctx)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for o, e in zip(outs, expected):
        np.testing.assert_allclose(o.asnumpy(), np.asarray(e), rtol=rtol,
                                   atol=atol)
    return outs


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-5, aux_states=None,
                            grad_req="write", ctx=None):
    """Backward input-gradients vs expected (reference: :1054)."""
    location = _as_location(sym, location)
    exe = _bind(sym, location, aux_states, grad_req=grad_req, ctx=ctx)
    exe.forward(is_train=True)
    exe.backward(out_grads=[nd.array(np.asarray(g)) for g in
                            (out_grads if isinstance(out_grads,
                                                     (list, tuple))
                             else [out_grads])])
    if isinstance(expected, dict):
        items = expected.items()
    else:
        items = zip(sym.list_arguments(), expected)
    for name, e in items:
        if e is None:
            continue
        np.testing.assert_allclose(
            exe.grad_dict[name].asnumpy(), np.asarray(e), rtol=rtol,
            atol=atol, err_msg="input gradient mismatch for %r" % name)
    return exe.grad_dict


def list_backends():
    """JAX platforms usable as consistency-check contexts."""
    import jax
    out = []
    for platform in ("cpu", "tpu", "gpu"):
        try:
            if jax.devices(platform):
                out.append(platform)
        except RuntimeError:
            pass
    return out


def _ctx_for(backend):
    return ctx_mod.cpu(0) if backend == "cpu" else \
        ctx_mod.Context("tpu" if backend == "tpu" else "gpu", 0)


def check_consistency(sym, location=None, shapes=None, aux_states=None,
                      backends=None, rtol=1e-4, atol=1e-5,
                      grad_req="write", seed=0):
    """Run the same symbol on every available backend and assert outputs
    and gradients agree — the cross-backend oracle
    (reference: test_utils.py:1207, cpu-vs-gpu there, cpu-vs-tpu here).

    When only one backend exists (CI runs on the CPU mesh), degrades to a
    determinism check: two independent executions must agree bitwise.
    """
    backends = backends or list_backends()
    if location is None or (shapes is not None
                            and isinstance(location, dict)):
        # shapes drive random values; an optional partial location dict
        # overrides specific inputs (index/range args that must be valid)
        rs = np.random.RandomState(seed)
        overrides = dict(location or {})
        location = {n: overrides.get(
            n, rs.normal(0, 1, s).astype(np.float32))
            for n, s in shapes.items()}
    else:
        location = _as_location(sym, location)
    rs = np.random.RandomState(seed + 1)
    results = []
    for backend in (backends if len(backends) > 1
                    else backends * 2):
        ctx = _ctx_for(backend)
        exe = _bind(sym, location, aux_states, grad_req=grad_req,
                    ctx=ctx)
        outs = exe.forward(is_train=True)
        if grad_req == "null":
            # forward-only op (integer/index outputs have no gradient)
            results.append(([o.asnumpy() for o in outs], {}, None,
                            backend))
            continue
        proj = [rs.normal(0, 1, o.shape).astype(np.float32)
                for o in outs] if not results else results[0][2]
        # cotangents must live on THIS executor's backend, not the
        # session-default device (mixed cpu+tpu sessions)
        exe.backward(out_grads=[nd.array(p, ctx=ctx) for p in proj])
        grads = {n: exe.grad_dict[n].asnumpy()
                 for n in exe.grad_dict}
        results.append(([o.asnumpy() for o in outs], grads, proj,
                        backend))
    ref_outs, ref_grads, _, ref_b = results[0]
    for outs, grads, _, b in results[1:]:
        for i, (o, r) in enumerate(zip(outs, ref_outs)):
            np.testing.assert_allclose(
                o, r, rtol=rtol, atol=atol,
                err_msg="output %d disagrees between %s and %s"
                        % (i, ref_b, b))
        for n in ref_grads:
            np.testing.assert_allclose(
                grads[n], ref_grads[n], rtol=rtol, atol=atol,
                err_msg="grad %r disagrees between %s and %s"
                        % (n, ref_b, b))
    return results


# ---------------------------------------------------------------------------
# tiny attention LM — the shared decode-workload fixture
# ---------------------------------------------------------------------------

def tiny_attention_lm(vocab=32, dim=16, seed=0, dtype="float32"):
    """A single-head attention language model sized for CPU CI — the
    shared fixture behind the paged-decode tests, ``bench.py
    --serve-decode`` and ``ci/decode_smoke.py``.

    Returns ``(params, step_fn, prefill_fn, token_spec, input_spec)``
    matching the :class:`mxnet_tpu.serve.DecodeEngine` contract:

    * ``step_fn(params, view, {"tok": (S,)}, pos)`` embeds the token,
      writes its K/V **exactly at position pos**, attends causally
      (everything past ``pos`` masked to -1e30 — positions beyond the
      cursor hold co-tenant garbage by design) and emits the greedy
      argmax next token, ``(S,) int32``;
    * ``prefill_fn`` computes K/V for a whole prompt prefix in one
      matrix product (row-wise bit-identical to the per-step path).

    The greedy emission makes every decode path — dense solo, paged
    batched ticks, speculative verify — comparable bit-for-bit on the
    token stream.
    """
    import jax
    import jax.numpy as jnp

    jdt = jnp.dtype(dtype)
    rs = np.random.RandomState(seed)
    params = {
        name: jnp.asarray(rs.randn(*shape).astype(np.float32) * 0.3,
                          jdt)
        for name, shape in (("E", (vocab, dim)), ("Wq", (dim, dim)),
                            ("Wk", (dim, dim)), ("Wv", (dim, dim)),
                            ("Wo", (dim, vocab)))}
    scale = jnp.asarray(1.0 / np.sqrt(dim), jdt)

    def step_fn(p, view, inputs, pos):
        tok = inputs["tok"]                    # (S,) int32
        x = p["E"][tok]                        # (S, D)
        q = x @ p["Wq"]
        k = x @ p["Wk"]
        v = x @ p["Wv"]
        idx = jnp.arange(view["k"].shape[0])
        nk = view["k"].at[idx, pos].set(k)     # write AT pos only
        nv = view["v"].at[idx, pos].set(v)
        seq = view["k"].shape[1]
        scores = jnp.einsum("sd,sld->sl", q, nk) * scale
        mask = jnp.arange(seq)[None, :] <= pos[:, None]
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, jdt))
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("sl,sld->sd", probs, nv)
        logits = ctx @ p["Wo"]
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return out, {"k": nk, "v": nv}

    def prefill_fn(p, inputs, length):
        toks = inputs["tok"][0]                # (Lr,)
        x = p["E"][toks]
        return {"k": (x @ p["Wk"])[None], "v": (x @ p["Wv"])[None]}

    token_spec = {"k": jax.ShapeDtypeStruct((dim,), jdt),
                  "v": jax.ShapeDtypeStruct((dim,), jdt)}
    input_spec = {"tok": jax.ShapeDtypeStruct((), jnp.int32)}
    return params, step_fn, prefill_fn, token_spec, input_spec


def dense_decode_reference(params, step_fn, prompt, n_new, padded_len,
                           dim, dtype="float32", input_name="tok",
                           cache_keys=("k", "v")):
    """Solo dense-cache greedy decode — THE bit-equality oracle for
    the paged decode path (tests/test_decode.py, ci/decode_smoke.py):
    the same ``step_fn`` over ONE dense worst-case cache
    ``(1, padded_len, dim)``, one dispatch per token.  The prompt is
    fed token by token at ``pos = t``; the LAST prompt token's output
    is the first generated token (matching the engine's
    prefill-prefix + first-tick convention).  Returns the generated
    token stream as a list of ints."""
    import jax
    import jax.numpy as jnp

    jdt = jnp.dtype(dtype)
    view = {k: jnp.zeros((1, padded_len, dim), jdt)
            for k in cache_keys}
    stepped = jax.jit(step_fn)
    cur, t = None, 0
    for tok in prompt:
        out, view = stepped(
            params, view, {input_name: jnp.asarray([tok], jnp.int32)},
            jnp.asarray([t], jnp.int32))
        t += 1
        cur = int(out[0])
    stream = []
    for _ in range(int(n_new)):
        stream.append(cur)
        if len(stream) >= int(n_new):
            break
        out, view = stepped(
            params, view, {input_name: jnp.asarray([cur], jnp.int32)},
            jnp.asarray([t], jnp.int32))
        t += 1
        cur = int(out[0])
    return stream
