"""Typed config spaces for the autotuner.

A :class:`ConfigSpace` is an ordered dict of named parameters; a
*config* is a plain JSON-able dict ``{param name: value}`` — the same
dict the :class:`~mxnet_tpu.autotune.store.TuningStore` persists and
the serving load path consults.  Scalar knob parameters are named
after their env var (``MXNET_SERVE_MAX_WAIT_MS``) so a stored config
maps onto the config-registry precedence chain without translation;
structured parameters (the bucket-ladder rung list) use their own
names (``ladder``).

Three parameter kinds:

* :class:`Choice` — a structured choice over an explicit option list
  (ladder rung tuples, block sizes);
* :class:`IntRange` / :class:`FloatRange` — scalar ranges with
  ``linear`` or ``log`` scale; log-scaled sampling draws uniformly in
  log space (the right prior for wait windows and byte caps whose
  interesting values span decades).

Everything is driven by a caller-owned ``random.Random`` — sampling
and neighborhood proposals are deterministic under a fixed seed,
which the search relies on for reproducible tuning runs.
"""

from __future__ import annotations

import math

from ..serve.buckets import MAX_BATCH_RUNG, ServeError

__all__ = ["Choice", "IntRange", "FloatRange", "ConfigSpace",
           "serve_space", "decode_space"]


class _Param(object):
    """One named tunable: sample a value, propose a neighbor,
    validate a stored value."""

    name = None
    default = None

    def sample(self, rng):
        raise NotImplementedError

    def neighbors(self, value, rng):
        """Local proposals around *value* (possibly empty)."""
        raise NotImplementedError

    def validate(self, value):
        """Typed/canonical form of *value*; raises ValueError when a
        stored config carries something outside the space."""
        raise NotImplementedError


class Choice(_Param):
    """A structured choice over an explicit, finite option list.

    Options are canonicalized through ``canon`` (default: identity;
    the ladder space passes ``tuple``) so JSON round-trips — which
    turn tuples into lists — still validate.
    """

    def __init__(self, name, options, default=None, canon=None):
        if not options:
            raise ValueError("Choice %r needs at least one option"
                             % name)
        self.name = name
        self._canon = canon or (lambda v: v)
        self.options = [self._canon(o) for o in options]
        self.default = self._canon(default) if default is not None \
            else self.options[0]
        if self.default not in self.options:
            raise ValueError("Choice %r default %r is not an option"
                             % (name, default))

    def sample(self, rng):
        return self.options[rng.randrange(len(self.options))]

    def neighbors(self, value, rng):
        value = self.validate(value)
        idx = self.options.index(value)
        out = []
        if idx > 0:
            out.append(self.options[idx - 1])
        if idx + 1 < len(self.options):
            out.append(self.options[idx + 1])
        return out

    def validate(self, value):
        value = self._canon(value)
        if value not in self.options:
            raise ValueError("%r is not an option of %r (have %r)"
                             % (value, self.name, self.options))
        return value


class _Range(_Param):
    """Shared machinery of the scalar ranges: uniform sampling on a
    linear or log scale, neighbors = one multiplicative (log) or
    additive (linear) step either way."""

    def __init__(self, name, lo, hi, default=None, scale="linear",
                 step=None):
        if scale not in ("linear", "log"):
            raise ValueError("scale must be 'linear' or 'log', got %r"
                             % (scale,))
        if hi < lo:
            raise ValueError("%r range [%r, %r] is empty"
                             % (name, lo, hi))
        if scale == "log" and lo <= 0:
            raise ValueError("%r: a log-scaled range needs lo > 0 "
                             "(got %r)" % (name, lo))
        self.name = name
        self.lo = lo
        self.hi = hi
        self.scale = scale
        # neighbor step: log = multiply/divide by step (default 2x),
        # linear = +/- step (default a tenth of the span)
        if step is None:
            step = 2.0 if scale == "log" else (hi - lo) / 10.0 or 1.0
        self.step = step
        self.default = self._clamp(default if default is not None
                                   else lo)

    def _cast(self, value):
        raise NotImplementedError

    def _clamp(self, value):
        return self._cast(min(self.hi, max(self.lo, value)))

    def sample(self, rng):
        if self.scale == "log":
            raw = math.exp(rng.uniform(math.log(self.lo),
                                       math.log(self.hi)))
        else:
            raw = rng.uniform(self.lo, self.hi)
        return self._clamp(raw)

    def neighbors(self, value, rng):
        value = self.validate(value)
        if self.scale == "log":
            cands = (value * self.step, value / self.step)
        else:
            cands = (value + self.step, value - self.step)
        out = []
        for c in cands:
            c = self._clamp(c)
            if c != value and c not in out:
                out.append(c)
        return out

    def validate(self, value):
        value = self._cast(value)
        if not (self.lo <= value <= self.hi):
            raise ValueError("%r=%r is outside [%r, %r]"
                             % (self.name, value, self.lo, self.hi))
        return value


class IntRange(_Range):
    def _cast(self, value):
        return int(round(value))


class FloatRange(_Range):
    def _cast(self, value):
        return float(value)


class ConfigSpace(object):
    """An ordered set of parameters + the operations the search
    needs: ``default()``, ``sample(rng)``, ``neighbors(config, rng)``
    (one param perturbed per proposal) and ``validate(config)``."""

    def __init__(self, params):
        self.params = {}
        for p in params:
            if p.name in self.params:
                raise ValueError("duplicate parameter %r" % p.name)
            self.params[p.name] = p

    def default(self):
        return {n: p.default for n, p in self.params.items()}

    def sample(self, rng):
        return {n: p.sample(rng) for n, p in self.params.items()}

    def neighbors(self, config, rng, limit=None):
        """Local proposals: every single-parameter perturbation of
        *config*, shuffled (deterministically under *rng*), capped at
        *limit*."""
        config = self.validate(config)
        out = []
        for n, p in self.params.items():
            for v in p.neighbors(config[n], rng):
                cand = dict(config)
                cand[n] = v
                out.append(cand)
        rng.shuffle(out)
        return out[:limit] if limit else out

    def validate(self, config):
        unknown = set(config) - set(self.params)
        if unknown:
            raise ValueError("config carries unknown parameters %s "
                             "(space has %s)"
                             % (sorted(unknown), sorted(self.params)))
        out = {}
        for n, p in self.params.items():
            if n not in config:
                raise ValueError("config lacks parameter %r" % n)
            out[n] = p.validate(config[n])
        return out

    def key(self, config):
        """Canonical hashable identity of a config (dedup across
        proposal rounds)."""
        config = self.validate(config)
        return tuple((n, tuple(v) if isinstance(v, (list, tuple))
                      else v) for n, v in sorted(config.items()))


def _ladder_choice(options, default):
    for opt in options:
        rungs = tuple(int(r) for r in opt)
        if any(b <= a for a, b in zip(rungs, rungs[1:])) or \
                rungs[0] < 1 or rungs[-1] > MAX_BATCH_RUNG:
            raise ServeError("ladder option %r is not a valid "
                             "ascending rung list" % (opt,))
    return Choice("ladder", options, default=default,
                  canon=lambda v: tuple(int(r) for r in v))


def serve_space(max_rows=16, ladders=None, max_wait_hi_ms=8.0):
    """The serve-workload space the CLI and CI tune over.

    * ``ladder`` — structured choice of rung lists (power-of-two,
      sparse, dense and deliberately non-power-of-two options; every
      option tops out >= *max_rows* so any trace request fits),
    * ``MXNET_SERVE_MAX_WAIT_MS`` — the coalescing window, linear
      ``[0, max_wait_hi_ms]`` (0 = dispatch immediately; the
      latency/throughput trade the tuner is really deciding),
    * ``MXNET_SERVE_MAX_BATCH`` — rows per coalesced dispatch as a
      structured choice (0 = the ladder's top rung),
    * ``quantize`` — serve the model fp32, int8-weight-only or full
      int8 (mxnet_tpu.quantize).  The measurer re-calibrates per
      candidate model and carries an accuracy guard: a quantized
      candidate whose outputs drift from fp32 measures ``ok=False``
      (infeasible), so with the default-``off`` baseline guard the
      tuner can never ship an accuracy- or latency-regressing
      quantization (docs/quantization.md).
    """
    if ladders is None:
        top = int(max_rows)
        ladders = [
            opt for opt in (
                (1, 2, 4, 8, 16),          # the hand-picked default
                (1, 2, 3, 4, 6, 8, 12, 16),  # dense, non-power-of-two
                (1, 3, 6, 16),             # sparse, non-power-of-two
                (1, 4, 16),                # sparse powers of four
                (2, 8, 16),                # no singleton rung
                (1, 2, 4, 8, 16, 32),      # the package default
            ) if opt[-1] >= top]
    return ConfigSpace([
        _ladder_choice(ladders, default=ladders[0]),
        FloatRange("MXNET_SERVE_MAX_WAIT_MS", 0.0, float(max_wait_hi_ms),
                   default=2.0, scale="linear",
                   step=max(0.5, float(max_wait_hi_ms) / 8.0)),
        Choice("MXNET_SERVE_MAX_BATCH", (0, 4, 8, 16), default=0,
               canon=int),
        Choice("quantize", ("off", "int8-weight-only", "int8"),
               default="off", canon=str),
    ])


def decode_space(block_sizes=(4, 8, 16, 32), rungs=None,
                 max_wait_hi_ms=8.0):
    """The decode-workload space: KV block size (structured choice —
    the pool reallocates per value, so it is not a smooth range),
    session-count tick rungs, and the idle-tick coalescing window."""
    if rungs is None:
        rungs = [(1, 2, 4, 8, 16), (1, 2, 3, 4, 6, 8, 12, 16),
                 (1, 4, 16), (1, 2, 4, 8, 16, 32)]
    return ConfigSpace([
        Choice("MXNET_SERVE_KV_BLOCK_SIZE", block_sizes,
               default=16 if 16 in block_sizes else block_sizes[0],
               canon=int),
        _ladder_choice(rungs, default=rungs[0]),
        FloatRange("MXNET_SERVE_DECODE_MAX_WAIT_MS", 0.0,
                   float(max_wait_hi_ms), default=2.0, scale="linear",
                   step=max(0.5, float(max_wait_hi_ms) / 8.0)),
    ])
