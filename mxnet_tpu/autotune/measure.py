"""Measurement harness — one candidate config, one replayed trace,
one number.

This is the autotuner's contact with reality: a candidate is scored
by replaying a recorded arrival trace through the REAL serving
machinery (CompiledPredictor + DynamicBatcher for serve,
DecodeEngine + DecodeBatcher for decode), never through a model of
it.  The trace supplies identical load to every candidate
(autotune/trace.py); the measurer supplies identical everything else:

* predictors are cached per ladder — two candidates differing only
  in scalar knobs share warm compiled programs, so a measurement
  prices the CONFIG, not a recompile;
* the persistent XLA compile cache (``MXNET_COMPILE_CACHE_DIR``)
  does the same across tuning processes;
* ``request_path_compiles`` rides along in every measurement — a
  candidate that compiles in the request path is broken, not slow,
  and the search treats its measurement as infeasible.

The analytic prior lives here too (:meth:`ServeMeasurer.prior`): the
:mod:`~mxnet_tpu.observability.costs` model prices each ladder
rung's lowered HLO, and a deterministic replay of the batcher's
coalescing discipline over the trace turns those rung costs into an
estimated p99 — dominated candidates are pruned before paying a real
measurement (search.py).
"""

from __future__ import annotations

import math

import numpy as _np

from . import trace as _trace
from ..serve.batcher import DynamicBatcher
from ..serve.buckets import BucketLadder, ServeError
from ..serve.predictor import CompiledPredictor

__all__ = ["ServeMeasurer", "DecodeMeasurer", "percentile",
           "fc_model"]

#: nominal roofline peaks for the analytic prior.  Only RATIOS matter
#: (the prior ranks candidates, it never claims wall-clock), so one
#: nominal machine is enough for every backend.
PRIOR_PEAK_FLOPS = 5e10
PRIOR_PEAK_BYTES_S = 2e10
#: fixed per-dispatch host overhead (seconds) in the prior's queue
#: replay — on tiny models the dispatch floor, not the FLOPs, is the
#: service time
PRIOR_DISPATCH_OVERHEAD_S = 25e-5


def percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (same discipline
    as bench.py — SLOs quote real request latencies)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def fc_model(dim, hidden=64, classes=16, seed=0):
    """The bench-family 2-layer FC inference model: returns
    ``(symbol, arg_params, data_shapes)`` for the measurers and the
    CI smoke (the same shape family bench.py --serve drives)."""
    from .. import nd, sym
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="atfc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="atfc2")
    net = sym.softmax(net)
    rs = _np.random.RandomState(seed)
    arg_shapes, _, _ = net.infer_shape(data=(1, dim))
    params = {n: nd.array(rs.randn(*s).astype(_np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n != "data"}
    return net, params, {"data": (1, dim)}


class ServeMeasurer(object):
    """Replays a serve trace against candidate (ladder, batcher-knob)
    configs.

    Parameters
    ----------
    trace : Trace (kind="serve")
    symbol, arg_params, data_shapes : optional
        The model under tuning; defaults to :func:`fc_model` at the
        trace's payload width.
    name : str
        Model name used in batcher/predictor labels and events.
    result_timeout : float
        Per-request result bound (seconds) — a wedged candidate fails
        its trial instead of hanging the search.
    """

    def __init__(self, trace, symbol=None, arg_params=None,
                 data_shapes=None, name="autotune", hidden=64,
                 classes=16, result_timeout=60.0):
        if trace.kind != "serve":
            raise ServeError("ServeMeasurer needs a serve trace, got "
                             "kind=%r" % trace.kind)
        self.trace = trace
        self.name = name
        self._timeout = float(result_timeout)
        if symbol is None:
            symbol, arg_params, data_shapes = fc_model(
                int(trace.meta["dim"]), hidden=hidden, classes=classes)
        self._symbol = symbol
        self._params = arg_params
        self._data_shapes = data_shapes
        self._predictors = {}     # (rungs, quantize) -> predictor
        self._rung_cost = {}      # rung -> analytic seconds (prior)
        self._quant_models = {}   # mode -> (qsym, qargs, qaux, report)
        self._quant_err = {}      # (rungs, mode) -> max rel err

    # -- shared warm predictors -------------------------------------------
    def _quantized_model(self, mode):
        """The model under tuning lowered at *mode* (cached — every
        candidate sharing a mode shares one calibration + lowering).
        Calibration runs on seeded batches of the trace's payload
        family, so the recorded calib sha identifies ranges the
        measurement actually exercised."""
        cached = self._quant_models.get(mode)
        if cached is None:
            from ..quantize import calibrate, quantize_model
            table = None
            if mode == "int8":
                rs = _np.random.RandomState(0)
                shape = next(iter(self._data_shapes.values()))
                table = calibrate(
                    self._symbol, self._params,
                    [rs.standard_normal((8,) + tuple(shape[1:]))
                     .astype(_np.float32) for _ in range(4)],
                    name=self.name)
            cached = quantize_model(self._symbol, self._params,
                                    calib=table, policy=mode,
                                    name=self.name)
            self._quant_models[mode] = cached
        return cached

    def predictor(self, rungs, quantize="off"):
        rungs = tuple(int(r) for r in rungs)
        mode = quantize or "off"
        pred = self._predictors.get((rungs, mode))
        if pred is None:
            if mode == "off":
                symbol, params = self._symbol, self._params
                aux = None
            else:
                symbol, params, aux, _report = \
                    self._quantized_model(mode)
            pred = CompiledPredictor(
                symbol, params, aux_params=aux,
                data_shapes=self._data_shapes,
                ladder=BucketLadder(batches=rungs), name=self.name)
            pred.warm()
            self._predictors[(rungs, mode)] = pred
        return pred

    def _quant_accuracy(self, rungs, mode):
        """Max rel err of the quantized predictor vs fp32 at the top
        rung (cached) — the measurement's accuracy guard."""
        key = (tuple(rungs), mode)
        err = self._quant_err.get(key)
        if err is None:
            rs = _np.random.RandomState(1)
            data = {n: rs.standard_normal((rungs[-1],) + tuple(s[1:]))
                    .astype(_np.float32)
                    for n, s in self._data_shapes.items()}
            q = self.predictor(rungs, mode).predict(data)
            f = self.predictor(rungs).predict(data)
            err = 0.0
            for qo, fo in zip(q, f):
                qa, fa = qo.asnumpy(), fo.asnumpy()
                denom = float(_np.abs(fa).max()) or 1.0
                err = max(err,
                          float(_np.abs(qa - fa).max()) / denom)
            self._quant_err[key] = err
        return err

    # -- real measurement --------------------------------------------------
    def measure(self, config, budget_frac=1.0):
        """Replay the trace (prefix) through a DynamicBatcher built
        from *config*.  Returns the measurement artifact dict; a shed
        or failed request marks it ``ok=False`` (the objective scores
        that infeasible)."""
        rungs = tuple(config.get("ladder") or
                      BucketLadder().batches)
        qmode = config.get("quantize") or "off"
        pred = self.predictor(rungs, qmode)
        quant_err = None if qmode == "off" \
            else self._quant_accuracy(rungs, qmode)
        compiles_warm = pred.compile_count
        batcher = DynamicBatcher(
            pred,
            max_wait_ms=config.get("MXNET_SERVE_MAX_WAIT_MS"),
            max_batch=config.get("MXNET_SERVE_MAX_BATCH"),
            name="%s-trial" % self.name)
        errors = 0
        try:
            def submit(payload, _i):
                try:
                    return batcher.submit(payload)
                except ServeError:
                    return None

            records, wall = _trace.replay(self.trace, submit,
                                          budget_frac)
            lats = []
            for _slot, t_sub, fut in records:
                if fut is None:
                    errors += 1
                    continue
                try:
                    fut.result(self._timeout)
                    lats.append(fut._t_resolved - t_sub)
                except Exception:
                    errors += 1
            batches = batcher.batch_count
        finally:
            batcher.close()
        lats.sort()
        n = len(records)
        sched = self.trace.schedule(budget_frac)
        duration = max(sched[-1][0], 1e-9)
        # the accuracy guard: a drifting quantized candidate is
        # INFEASIBLE, not merely slow — the objective never trades
        # correctness for latency (docs/quantization.md)
        acc_ok = quant_err is None or quant_err <= 0.1
        quant_fields = {}
        if qmode != "off":
            report = self._quant_models[qmode][3]
            quant_fields = {
                "quantize": qmode,
                "calib_sha": report.get("calib_sha"),
                "quant_max_rel_err": round(quant_err, 6),
            }
        return {
            "workload": "serve",
            "ok": errors == 0 and bool(lats) and acc_ok,
            "requests": n,
            **quant_fields,
            "errors": errors,
            "budget_frac": float(budget_frac),
            "offered_rps": round((n - 1) / duration, 2) if n > 1
            else None,
            "achieved_rps": round(len(lats) / wall, 2) if wall > 0
            else 0.0,
            "p50_ms": round(percentile(lats, 50) * 1e3, 3)
            if lats else None,
            "p99_ms": round(percentile(lats, 99) * 1e3, 3)
            if lats else None,
            "batches": batches,
            "request_path_compiles":
                pred.compile_count - compiles_warm,
            "wall_s": round(wall, 3),
        }

    # -- analytic prior ----------------------------------------------------
    def rung_cost_s(self, rung):
        """Analytic service seconds of one dispatch at *rung* rows:
        the rung program's lowered HLO priced by the
        ``observability.costs`` roofline model against the nominal
        peaks, plus the fixed dispatch overhead."""
        rung = int(rung)
        cost = self._rung_cost.get(rung)
        if cost is None:
            from ..observability import costs as _costs
            pred = self.predictor((rung,) if rung == 1
                                  else (1, rung))
            shapes = {n: (rung,) + tuple(s[1:])
                      for n, s in self._data_shapes.items()}
            pa, aa, da, ka = pred._avals(shapes)
            text = pred._jit.lower(pa, aa, da, ka).as_text()
            table = _costs.cost_table(
                text=text, peak_flops=PRIOR_PEAK_FLOPS,
                peak_bytes_s=PRIOR_PEAK_BYTES_S)
            cost = max(table["total_flops"] / PRIOR_PEAK_FLOPS,
                       table["total_bytes"] / PRIOR_PEAK_BYTES_S) \
                + PRIOR_DISPATCH_OVERHEAD_S
            self._rung_cost[rung] = cost
        return cost

    def prior(self, config, budget_frac=1.0):
        """Estimated p99 latency (ms) of *config* on this trace: a
        deterministic replay of the batcher's coalescing discipline —
        FIFO queue, coalescing window from the oldest queued request,
        row cap, pad-to-rung — with rung service times from
        :meth:`rung_cost_s`.  No measurement, no threads; used to
        prune dominated candidates before paying a real replay."""
        ladder = BucketLadder(batches=tuple(
            config.get("ladder") or BucketLadder().batches))
        wait = max(0.0, float(
            config.get("MXNET_SERVE_MAX_WAIT_MS") or 0.0)) / 1e3
        cap = int(config.get("MXNET_SERVE_MAX_BATCH") or 0) \
            or ladder.max_batch
        cap = min(cap, ladder.max_batch)
        sched = self.trace.schedule(budget_frac)
        lats = []
        t_free = 0.0
        i = 0
        n = len(sched)
        while i < n:
            head_t = sched[i][0]
            # the window closes wait seconds after the OLDEST queued
            # request; a busy dispatcher extends it for free
            close = max(head_t + wait, t_free)
            batch = [i]
            rows = sched[i][1]
            j = i + 1
            while j < n and rows < cap:
                t_j, r_j = sched[j]
                if t_j > close or rows + r_j > cap:
                    break
                batch.append(j)
                rows += r_j
                j += 1
            last_arrival = sched[batch[-1]][0]
            dispatch_at = max(t_free, last_arrival,
                              close if rows < cap else last_arrival)
            done = dispatch_at + self.rung_cost_s(
                ladder.batch_for(rows))
            for k in batch:
                lats.append(done - sched[k][0])
            t_free = done
            i = j
        lats.sort()
        return percentile(lats, 99) * 1e3

    def close(self):
        self._predictors.clear()


class DecodeMeasurer(object):
    """Replays a decode-session trace against candidate (KV block
    size, session rungs, tick window) configs.  Model defaults to
    ``test_utils.tiny_attention_lm`` at the trace's vocab."""

    def __init__(self, trace, model=None, dim=24, name="autotune",
                 result_timeout=120.0):
        if trace.kind != "decode":
            raise ServeError("DecodeMeasurer needs a decode trace, "
                             "got kind=%r" % trace.kind)
        self.trace = trace
        self.name = name
        self._timeout = float(result_timeout)
        if model is None:
            from ..test_utils import tiny_attention_lm
            model = tiny_attention_lm(vocab=int(trace.meta["vocab"]),
                                      dim=dim, seed=0)
        (self._params, self._step_fn, self._prefill_fn,
         self._token_spec, self._input_spec) = model
        self._engines = {}    # (block_size, rungs) -> DecodeEngine

    def engine(self, block_size, rungs):
        import warnings
        from ..serve.decode import DecodeEngine
        key = (int(block_size), tuple(int(r) for r in rungs))
        eng = self._engines.get(key)
        if eng is None:
            plens = [p for _, p in self.trace.schedule()]
            max_len = max(plens) + int(
                self.trace.meta.get("new_tokens", 24)) + 1
            blocks_each = -(-max_len // int(block_size))
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")  # CPU ignores donation
                eng = DecodeEngine(
                    self._step_fn, self._prefill_fn, self._token_spec,
                    self._input_spec, params=self._params,
                    max_len=max_len, block_size=int(block_size),
                    num_blocks=len(plens) * blocks_each + 2,
                    session_rungs=key[1], donate=True,
                    label="%s-b%d" % (self.name, key[0]))
            self._engines[key] = eng
        return eng

    def measure(self, config, budget_frac=1.0):
        from ..serve.decode import DecodeBatcher
        eng = self.engine(
            config.get("MXNET_SERVE_KV_BLOCK_SIZE") or 16,
            tuple(config.get("ladder") or (1, 2, 4, 8, 16)))
        warm = eng.compile_count
        new_tokens = int(self.trace.meta.get("new_tokens", 24))
        batcher = DecodeBatcher(
            eng, max_wait_ms=config.get(
                "MXNET_SERVE_DECODE_MAX_WAIT_MS"),
            name="%s-trial" % self.name)
        errors = 0
        try:
            def submit(prompt, _i):
                try:
                    return batcher.start({"tok": prompt},
                                         max_new_tokens=new_tokens)
                except Exception:
                    return None

            records, wall = _trace.replay(self.trace, submit,
                                          budget_frac)
            total_tokens = 0
            ttft, token_lat = [], []
            for _slot, t_sub, sess in records:
                if sess is None:
                    errors += 1
                    continue
                try:
                    sess.result(self._timeout)
                except Exception:
                    errors += 1
                    continue
                stamps = sess.stamps()
                total_tokens += len(stamps)
                if stamps:
                    ttft.append(stamps[0] - t_sub)
                    token_lat.append(stamps[0] - t_sub)
                    token_lat.extend(b - a for a, b in
                                     zip(stamps, stamps[1:]))
            ticks = batcher.tick_count
        finally:
            batcher.close()
        token_lat.sort()
        ttft.sort()
        return {
            "workload": "decode",
            "ok": errors == 0 and total_tokens > 0,
            "sessions": len(records),
            "errors": errors,
            "budget_frac": float(budget_frac),
            "total_tokens": total_tokens,
            "tokens_per_sec": round(total_tokens / wall, 2)
            if wall > 0 else 0.0,
            "ticks": ticks,
            "token_p99_ms": round(percentile(token_lat, 99) * 1e3, 3)
            if token_lat else None,
            "ttft_p99_ms": round(percentile(ttft, 99) * 1e3, 3)
            if ttft else None,
            "request_path_compiles": eng.compile_count - warm,
            "wall_s": round(wall, 3),
        }

    def prior(self, config, budget_frac=1.0):
        """No analytic prior for decode yet (the tick loop's cost is
        dominated by cross-tick cache state the HLO-table model does
        not see); every decode candidate is measured."""
        return None

    def close(self):
        for eng in self._engines.values():
            eng.close()
        self._engines.clear()
