"""Successive-halving search over a config space, measured cost only.

The loop (TVM's lesson, sized for a knob space rather than a kernel
schedule space):

1. **Propose** — the space's default config plus random samples
   (dedup by canonical config key).
2. **Prune on the analytic prior** — when the measurer provides one
   (``observability.costs`` roofline pricing of each rung + a
   deterministic replay of the coalescing discipline), candidates
   whose estimated objective is dominated — worse than
   ``prune_ratio`` x the best estimate — are dropped WITHOUT paying
   a measurement.  The prior only ever prunes, never picks: every
   surviving ranking decision is measured.
3. **Short replays** — every survivor replays the first
   ``short_frac`` of the trace; rank by the objective.
4. **Neighborhood proposals** — local perturbations of the
   short-round leader join at short budget (prior-pruned too).
5. **Promote** — the top ``1/eta`` (>= ``min_promote``) graduate to
   FULL replays; the winner is the best full-replay score.
6. **Baseline guard** — the space default is ALWAYS measured at full
   budget on the same trace; if no candidate beats it, the default
   IS the winner (gain 0) — tuning can only help, never regress.

Every trial emits an ``autotune`` event (trial_start / trial_result
/ pruned / promoted / winner, each with the config and score) and
bumps ``autotune_trials_total`` / ``autotune_prune_total``; the
winning entry is persisted to the :class:`TuningStore` WITH its
measurement artifact (winner + baseline + trace identity + search
stats).
"""

from __future__ import annotations

import math
import random

from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["Objective", "serve_objective", "decode_objective",
           "tune", "INFEASIBLE"]

_TRIALS_TOTAL = _obs_metrics.counter(
    "autotune_trials_total",
    "autotune candidate measurements paid (short + full replays)")
_PRUNE_TOTAL = _obs_metrics.counter(
    "autotune_prune_total",
    "autotune candidates pruned by the analytic-cost prior without "
    "a measurement")

INFEASIBLE = float("inf")


class Objective(object):
    """Scores a measurement artifact; LOWER IS ALWAYS BETTER (a
    maximize-this metric negates).  ``spec`` is the JSON-able
    description persisted with the winning entry."""

    def __init__(self, name, score_fn, spec=None):
        self.name = name
        self._score_fn = score_fn
        self.spec = dict(spec or {}, name=name)

    def score(self, measurement):
        if not measurement or not measurement.get("ok"):
            return INFEASIBLE
        if measurement.get("request_path_compiles"):
            # a config that compiles in the request path is broken,
            # not slow — it must never win
            return INFEASIBLE
        s = self._score_fn(measurement)
        return INFEASIBLE if s is None else float(s)

    def gain_pct(self, winner_score, baseline_score):
        """Relative improvement of winner over baseline (positive =
        better), on the objective's own scale."""
        if not math.isfinite(winner_score) or \
                not math.isfinite(baseline_score) or \
                baseline_score == 0:
            return 0.0
        return round((baseline_score - winner_score)
                     / abs(baseline_score) * 100.0, 2)


def serve_objective(throughput_floor=0.85):
    """p99 latency under a throughput floor: a candidate whose
    achieved rate fell below ``floor x offered`` shed or stalled its
    way to a pretty p99 and is infeasible."""
    floor = float(throughput_floor)

    def score(m):
        offered = m.get("offered_rps")
        achieved = m.get("achieved_rps")
        if offered and (achieved or 0.0) < floor * offered:
            return None
        return m.get("p99_ms")

    return Objective("serve_p99_ms", score,
                     spec={"throughput_floor": floor,
                           "metric": "p99_ms", "mode": "min"})


def decode_objective():
    """Aggregate decode throughput (tokens/sec, maximized)."""
    def score(m):
        tps = m.get("tokens_per_sec")
        return -tps if tps else None

    return Objective("decode_neg_tokens_per_sec", score,
                     spec={"metric": "tokens_per_sec", "mode": "max"})


def _jsonable(config):
    return {k: (list(v) if isinstance(v, tuple) else v)
            for k, v in config.items()}


def _ev_score(score):
    return None if not math.isfinite(score) else round(score, 4)


def tune(space, measurer, objective, *, model, workload,
         trials=12, neighbor_trials=4, seed=0, short_frac=0.25,
         eta=2, min_promote=2, prune_ratio=3.0, min_keep=4,
         store=None, device=None, log=None):
    """Run the search; returns the result dict (and persists the
    winning entry when *store* is given).

    Parameters
    ----------
    space : ConfigSpace
    measurer : object with ``measure(config, budget_frac)`` and
        ``prior(config, budget_frac) -> float | None`` (None = no
        prior, nothing pruned).
    objective : Objective
    model, workload : str
        The store key (with *device*, default-detected).
    trials : int
        Random proposals measured at short budget (incl. default).
    neighbor_trials : int
        Neighborhood proposals around the short-round leader.
    short_frac : float
        Trace fraction of the cheap screening replays.
    eta, min_promote : successive-halving promotion shape.
    prune_ratio, min_keep : analytic-prior pruning (a candidate is
        pruned when its estimate exceeds ``prune_ratio`` x the best
        estimate, but at least ``min_keep`` candidates survive).
    """
    rng = random.Random(seed)
    log = log or (lambda *_a: None)
    emit = _obs_events.emitter("autotune")

    def propose_random(count, seen):
        out = []
        attempts = 0
        while len(out) < count and attempts < count * 20:
            attempts += 1
            cand = space.sample(rng)
            k = space.key(cand)
            if k not in seen:
                seen.add(k)
                out.append(cand)
        return out

    def prior_prune(cands, keep_always):
        """Split candidates into (kept, pruned) on the analytic
        prior.  *keep_always* keys are never pruned (the default
        config: it is the baseline, it must be measured)."""
        priors = []
        for c in cands:
            try:
                priors.append(measurer.prior(c, short_frac))
            except Exception:
                priors.append(None)
        known = [p for p in priors if p is not None]
        if not known:
            return cands, []
        best = min(known)
        ranked = sorted(range(len(cands)),
                        key=lambda i: (priors[i]
                                       if priors[i] is not None
                                       else best))
        keep_floor = {i for i in ranked[:min_keep]}
        kept, pruned = [], []
        for i, c in enumerate(cands):
            p = priors[i]
            dominated = (p is not None and best > 0
                         and p > prune_ratio * best
                         and i not in keep_floor
                         and space.key(c) not in keep_always)
            if dominated:
                pruned.append((c, p))
            else:
                kept.append(c)
        for c, p in pruned:
            _PRUNE_TOTAL.inc()
            emit(kind="pruned", model=model, workload=workload,
                 config=_jsonable(c), prior=round(p, 4),
                 prior_best=round(best, 4))
            log("pruned (prior %.2f vs best %.2f): %r"
                % (p, best, _jsonable(c)))
        return kept, pruned

    def run_trial(config, budget):
        _TRIALS_TOTAL.inc()
        emit(kind="trial_start", model=model, workload=workload,
             config=_jsonable(config), budget_frac=budget)
        try:
            meas = measurer.measure(config, budget)
        except Exception as exc:
            meas = {"ok": False,
                    "error": "%s: %s" % (type(exc).__name__,
                                         str(exc)[:200])}
        s = objective.score(meas)
        emit(kind="trial_result", model=model, workload=workload,
             config=_jsonable(config), budget_frac=budget,
             score=_ev_score(s), ok=bool(meas.get("ok")))
        log("trial budget=%.2f score=%s %r"
            % (budget, _ev_score(s), _jsonable(config)))
        return meas, s

    default = space.default()
    default_key = space.key(default)
    seen = {default_key}
    candidates = [default] + propose_random(max(0, trials - 1), seen)

    kept, pruned_round1 = prior_prune(candidates, {default_key})
    n_pruned = len(pruned_round1)

    # -- short replays (screening) --------------------------------------
    short = [(c,) + run_trial(c, short_frac) for c in kept]
    short.sort(key=lambda t: t[2])

    # -- neighborhood proposals around the leader -----------------------
    leader = short[0][0]
    neigh = []
    for cand in space.neighbors(leader, rng):
        k = space.key(cand)
        if k not in seen:
            seen.add(k)
            neigh.append(cand)
        if len(neigh) >= neighbor_trials:
            break
    neigh, pruned_n = prior_prune(neigh, set())
    n_pruned += len(pruned_n)
    short += [(c,) + run_trial(c, short_frac) for c in neigh]
    short.sort(key=lambda t: t[2])

    # -- promotion to full replays --------------------------------------
    feasible = [t for t in short if math.isfinite(t[2])]
    n_promote = max(min_promote, int(math.ceil(len(short) / eta)))
    promoted = feasible[:n_promote] or short[:1]
    for c, _m, s in promoted:
        emit(kind="promoted", model=model, workload=workload,
             config=_jsonable(c), short_score=_ev_score(s))

    full = {}
    for c, _m, _s in promoted:
        meas, s = run_trial(c, 1.0)
        full[space.key(c)] = (c, meas, s)

    # the baseline (space default) always gets a full-budget
    # measurement on the same trace — the gain is quoted against it
    if default_key in full:
        baseline_meas, baseline_score = full[default_key][1:]
    else:
        baseline_meas, baseline_score = run_trial(default, 1.0)

    winner, winner_meas, winner_score = min(
        full.values(), key=lambda t: t[2])
    if not math.isfinite(winner_score) or \
            winner_score > baseline_score:
        # nothing beat the default on the full replay: the default IS
        # the winner — a tuning run must never ship a regression
        winner, winner_meas, winner_score = \
            default, baseline_meas, baseline_score

    gain = objective.gain_pct(winner_score, baseline_score)
    n_trials = len(short) + len(full) + \
        (0 if default_key in full else 1)
    result = {
        "model": model, "workload": workload,
        "device_kind": device or _device(),
        "config": winner,
        "score": _ev_score(winner_score),
        "baseline_config": default,
        "baseline_score": _ev_score(baseline_score),
        "gain_pct": gain,
        "trials": n_trials,
        "pruned": n_pruned,
        "objective": objective.spec,
        "measurement": winner_meas,
        "baseline": baseline_meas,
        "trace": measurer.trace.summary(),
        "search": {"seed": seed, "trials": n_trials,
                   "pruned": n_pruned, "short_frac": short_frac,
                   "eta": eta, "promoted": len(full)},
    }
    emit(kind="winner", model=model, workload=workload,
         config=_jsonable(winner), score=_ev_score(winner_score),
         baseline_score=_ev_score(baseline_score), gain_pct=gain,
         trials=n_trials, pruned=n_pruned)
    log("winner score=%s baseline=%s gain=%.2f%% %r"
        % (_ev_score(winner_score), _ev_score(baseline_score), gain,
           _jsonable(winner)))

    if store is not None:
        entry = store.put(
            model, workload, _jsonable(winner),
            device=result["device_kind"],
            score=result["score"],
            baseline_score=result["baseline_score"],
            gain_pct=gain, objective=objective.spec,
            trace=result["trace"], measurement=winner_meas,
            baseline=baseline_meas, search=result["search"])
        store.save()
        result["entry"] = entry
        result["store_path"] = store.path
    return result


def _device():
    from .store import device_kind
    return device_kind()
