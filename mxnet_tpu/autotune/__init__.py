"""Measured-cost autotuning over the serving knob space (ROADMAP
item 6 — the TVM lesson: search over *measured* cost beats hand
tuning).

The package is three small, separable pieces plus the measurement
harness that binds them to the serving subsystem:

* :mod:`~mxnet_tpu.autotune.space` — typed config spaces: ladder
  rung lists as structured choices, scalar knobs as log/linear
  ranges, with deterministic sampling and neighborhood proposals;
* :mod:`~mxnet_tpu.autotune.trace` — recorded, replayable open-loop
  arrival traces (request sizes + arrival offsets; decode: prompt
  lengths + session arrivals) so two candidates see IDENTICAL load;
* :mod:`~mxnet_tpu.autotune.store` — the JSON ``TuningStore`` keyed
  ``(model_name, device_kind, workload)``, each winner persisted WITH
  the measurement artifact that justified it;
* :mod:`~mxnet_tpu.autotune.search` — successive-halving search
  (random + neighborhood proposals, short replays promote to full
  replays) with the :mod:`~mxnet_tpu.observability.costs` analytic
  model as a prior that prunes dominated candidates before paying a
  measurement;
* :mod:`~mxnet_tpu.autotune.measure` — replays a trace against one
  candidate through the real registry/batcher/decode request path.

``tools/autotune.py`` is the CLI; ``ModelRegistry.load`` /
``DynamicBatcher`` / ``DecodeEngine`` consult the store at load time
with precedence explicit env > tuned store > registered default
(docs/autotuning.md).
"""

from __future__ import annotations

from .space import Choice, ConfigSpace, FloatRange, IntRange, \
    decode_space, serve_space
from .store import TuningStore, active_store, device_kind, lookup
from .search import Objective, decode_objective, serve_objective, tune
from .trace import Trace, synth_decode_trace, synth_serve_trace

__all__ = [
    "Choice", "ConfigSpace", "FloatRange", "IntRange",
    "serve_space", "decode_space",
    "TuningStore", "active_store", "device_kind", "lookup",
    "Objective", "serve_objective", "decode_objective", "tune",
    "Trace", "synth_serve_trace", "synth_decode_trace",
]
