"""The JSON ``TuningStore`` — winning configs, with receipts.

One store file holds every tuning the search has won, keyed
``(model_name, device_kind, workload)``.  An entry is never just a
config: it carries the **measurement artifact that justified it** —
the winner's measured objective, the default config's objective on
the SAME replayed trace, the gain, the trace identity (sha256 +
summary) and the trial/prune counts — so "why is production running
max_wait=0.4ms?" is answered by the store itself, not by archaeology.

Consumers (``ModelRegistry.load``, ``DynamicBatcher``,
``DecodeEngine``) consult the store named by the
``MXNET_TUNING_STORE`` env knob through :func:`lookup`; precedence at
every knob is explicit env > tuned store > registered default
(``config.resolve_env``).  An empty knob means zero lookups and zero
overhead.  Writes are atomic replaces (``resilience.checkpoint``
machinery) — a torn store must not exist.
"""

from __future__ import annotations

import json
import os
import time

from ..resilience.checkpoint import atomic_write

__all__ = ["TuningStore", "TuningStoreError", "active_store",
           "lookup", "device_kind", "install"]

_FORMAT = 1


class TuningStoreError(ValueError):
    """A store file that does not parse or does not validate."""


def device_kind():
    """The canonical device-kind string entries are keyed on (e.g.
    ``"cpu"``, ``"TPU v4"``).  Falls back to ``"cpu"`` when no
    backend is importable — tuning keys must never crash a load."""
    try:
        import jax
        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", dev.platform))
    except Exception:
        return "cpu"


def _key(model, device, workload):
    return "%s|%s|%s" % (model, device, workload)


class TuningStore(object):
    """Load/put/get/save over one JSON store file.

    The in-memory form is a dict ``key -> entry``; an entry is a
    plain dict with at least ``model`` / ``device_kind`` /
    ``workload`` / ``config``, and (for search-written entries)
    ``score`` / ``baseline_score`` / ``gain_pct`` / ``objective`` /
    ``trace`` / ``measurement`` / ``baseline`` / ``search``.
    """

    def __init__(self, path, entries=None):
        self.path = path
        self._entries = dict(entries or {})

    # -- persistence -------------------------------------------------------
    @classmethod
    def load(cls, path, missing_ok=False):
        if not os.path.exists(path):
            if missing_ok:
                return cls(path)
            raise TuningStoreError("no tuning store at %r" % (path,))
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            raise TuningStoreError("cannot read tuning store %r: %s"
                                   % (path, exc))
        if not isinstance(doc, dict) or doc.get("format") != _FORMAT:
            raise TuningStoreError(
                "%r is not a format-%d tuning store" % (path, _FORMAT))
        entries = {}
        for e in doc.get("entries", []):
            for field in ("model", "device_kind", "workload", "config"):
                if field not in e:
                    raise TuningStoreError(
                        "store entry lacks %r: %r" % (field, e))
            entries[_key(e["model"], e["device_kind"],
                         e["workload"])] = e
        return cls(path, entries)

    def save(self, path=None):
        path = path or self.path
        doc = {"format": _FORMAT,
               "entries": [self._entries[k]
                           for k in sorted(self._entries)]}
        atomic_write(path, (json.dumps(doc, indent=1, sort_keys=True)
                            + "\n").encode("utf-8"))
        return path

    # -- access ------------------------------------------------------------
    def get(self, model, workload, device=None):
        """The entry for ``(model, device, workload)`` or None.  A
        device-specific entry wins over an ``"any"``-device one (a
        store shipped across heterogeneous fleets)."""
        device = device or device_kind()
        return self._entries.get(_key(model, device, workload)) \
            or self._entries.get(_key(model, "any", workload))

    def put(self, model, workload, config, device=None, **artifact):
        """Install/replace the entry for the key; *artifact* is the
        measurement record persisted verbatim alongside the config."""
        device = device or device_kind()
        entry = {"model": model, "device_kind": device,
                 "workload": workload, "config": dict(config),
                 "created": round(time.time(), 3)}
        entry.update(artifact)
        self._entries[_key(model, device, workload)] = entry
        return entry

    def entries(self):
        return [self._entries[k] for k in sorted(self._entries)]

    def __len__(self):
        return len(self._entries)


# -- the env-named store the serving path consults ---------------------------

# tiny cache so a registry loading N models reads the file once per
# mtime, not N times; (path, mtime) -> TuningStore
_cache = {}


def active_store():
    """The store named by ``MXNET_TUNING_STORE``, or None (unset knob
    = no store, no file IO).  A missing or corrupt file is a loud
    failure — a deploy pointing at a store that is not there should
    not silently run defaults."""
    from ..config import get_env
    path = get_env("MXNET_TUNING_STORE")
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        raise TuningStoreError(
            "MXNET_TUNING_STORE=%r but no store file is there" % path)
    cached = _cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    store = TuningStore.load(path)
    _cache.clear()          # one active path at a time is the reality
    _cache[path] = (mtime, store)
    return store


def lookup(model, workload, device=None):
    """The active store's entry for ``(model, device, workload)``,
    or None when no store is configured / no entry matches."""
    store = active_store()
    if store is None:
        return None
    return store.get(model, workload, device=device)


def install(entry):
    """Apply a store entry's scalar knobs to the process-wide tuned
    layer (``config.tuned_override``) — the single-model replica
    path, where one tuning owns the process.  Structured params
    (``ladder``) are not env knobs and are skipped; returns the
    installed names.  Exported env vars still win at read time."""
    from ..config import _REGISTRY, tuned_override
    installed = []
    for name, value in (entry.get("config") or {}).items():
        if name in _REGISTRY:
            tuned_override(name, value)
            installed.append(name)
    return installed
