"""Recorded, replayable open-loop arrival traces.

A trace is the LOAD, separated from the measurement: the complete
arrival schedule of an open-loop run — for the serve workload every
request's ``(arrival offset, rows)``, for decode every session's
``(arrival offset, prompt length)`` — plus the payload seed.  Two
replays of the same trace submit byte-identical payloads at identical
offsets in identical order, so two candidate configs (or two builds a
perf bisect apart) see IDENTICAL offered load; the only thing that
differs is how the system under test responds.  That determinism is
what makes an autotune comparison (and a recorded perf regression)
trustworthy, and it is proven in tests/test_autotune.py.

Payloads are NOT stored: they are re-materialized from ``seed`` with
a fresh ``numpy.random.RandomState`` walked over the event list in
order — same schedule prefix, same payload bytes, while the trace
file stays a few KB of JSON.

The arrival grid is open-loop by construction: replay sleeps until
each event's offset and never waits for the system under test, so a
backed-up batcher accumulates queueing latency instead of silently
slowing the offered rate (no coordinated omission).
"""

from __future__ import annotations

import hashlib
import json
import math
import time

import numpy as _np

from ..resilience.checkpoint import atomic_write

__all__ = ["Trace", "TraceError", "synth_serve_trace",
           "synth_decode_trace", "replay"]

_FORMAT = 1


class TraceError(ValueError):
    """A trace file that does not parse or does not validate."""


class Trace(object):
    """One recorded arrival schedule.

    Parameters
    ----------
    kind : str
        ``"serve"`` (events carry ``rows``) or ``"decode"`` (events
        carry ``prompt_len``).
    events : list of dict
        ``{"t": offset seconds from replay start, "rows"|"prompt_len":
        int}``, offsets non-decreasing.
    meta : dict
        Workload geometry the payloads depend on (``dim`` for serve;
        ``vocab`` for decode) plus whatever the recorder wants to keep
        (offered rate, recorder name).
    seed : int
        Seed of the payload re-materialization walk.
    """

    def __init__(self, kind, events, meta=None, seed=0):
        if kind not in ("serve", "decode"):
            raise TraceError("trace kind must be 'serve' or 'decode', "
                             "got %r" % (kind,))
        field = "rows" if kind == "serve" else "prompt_len"
        evs = []
        last_t = 0.0
        for i, e in enumerate(events):
            t = float(e["t"])
            n = int(e[field])
            if t < last_t:
                raise TraceError(
                    "event %d arrives at %.6f, before its predecessor "
                    "at %.6f — offsets must be non-decreasing"
                    % (i, t, last_t))
            if n < 1:
                raise TraceError("event %d has %s=%d (must be >= 1)"
                                 % (i, field, n))
            evs.append({"t": t, field: n})
            last_t = t
        if not evs:
            raise TraceError("a trace needs at least one event")
        self.kind = kind
        self.events = evs
        self.meta = dict(meta or {})
        self.seed = int(seed)

    # -- identity ----------------------------------------------------------
    def schedule(self, budget_frac=1.0):
        """The (offset, size) pairs a replay at *budget_frac* submits:
        the first ``ceil(frac * len)`` events.  This IS the replayed
        schedule — the determinism test asserts two calls are equal."""
        field = "rows" if self.kind == "serve" else "prompt_len"
        n = len(self.events)
        take = max(1, min(n, int(math.ceil(n * float(budget_frac)))))
        return [(e["t"], e[field]) for e in self.events[:take]]

    def payloads(self, budget_frac=1.0):
        """Deterministically re-materialized payload arrays for the
        replayed prefix: serve = float32 ``(rows, dim)`` request
        arrays, decode = int32 prompt-token arrays in ``[0, vocab)``.
        One RandomState walked over the events IN ORDER — a shorter
        budget gets the exact prefix of the full run's payloads."""
        rs = _np.random.RandomState(self.seed)
        out = []
        if self.kind == "serve":
            dim = int(self.meta.get("dim", 0))
            if dim < 1:
                raise TraceError("serve trace lacks meta.dim (payload "
                                 "width)")
            for _, rows in self.schedule(budget_frac):
                out.append(rs.randn(rows, dim).astype(_np.float32))
        else:
            vocab = int(self.meta.get("vocab", 0))
            if vocab < 1:
                raise TraceError("decode trace lacks meta.vocab")
            for _, plen in self.schedule(budget_frac):
                out.append(rs.randint(0, vocab, size=plen)
                           .astype(_np.int32))
        return out

    def duration(self, budget_frac=1.0):
        return self.schedule(budget_frac)[-1][0]

    def sha256(self):
        """Content hash of the canonical serialization — the store
        records it so a winning artifact names exactly which load it
        was measured under."""
        return hashlib.sha256(
            self._canonical().encode("utf-8")).hexdigest()

    def _canonical(self):
        return json.dumps(self._to_doc(), sort_keys=True,
                          separators=(",", ":"))

    # -- (de)serialization -------------------------------------------------
    def _to_doc(self):
        return {"format": _FORMAT, "kind": self.kind,
                "seed": self.seed, "meta": self.meta,
                "events": self.events}

    def save(self, path):
        """Write the trace as JSON (atomic replace — a torn trace
        file must not exist)."""
        atomic_write(path, (json.dumps(self._to_doc(), indent=1,
                                       sort_keys=True) + "\n")
                     .encode("utf-8"))
        return path

    @classmethod
    def load(cls, path):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            raise TraceError("cannot read trace %r: %s" % (path, exc))
        if not isinstance(doc, dict) or doc.get("format") != _FORMAT:
            raise TraceError(
                "%r is not a format-%d trace file (got format=%r)"
                % (path, _FORMAT, doc.get("format")
                   if isinstance(doc, dict) else None))
        return cls(doc.get("kind"), doc.get("events") or [],
                   meta=doc.get("meta"), seed=doc.get("seed", 0))

    def summary(self):
        sched = self.schedule()
        sizes = [n for _, n in sched]
        return {"kind": self.kind, "events": len(sched),
                "duration_s": round(self.duration(), 4),
                "sha256": self.sha256(),
                "size_min": min(sizes), "size_max": max(sizes),
                "seed": self.seed}

    def __repr__(self):
        return "Trace(kind=%r, events=%d, duration=%.3fs)" % (
            self.kind, len(self.events), self.duration())


def synth_serve_trace(rate=150.0, seconds=2.0, dim=64, rows_lo=1,
                      rows_hi=4, seed=0):
    """A synthetic serve schedule matching bench.py's open loop: a
    fixed arrival grid at *rate* with mixed request sizes drawn
    uniformly in ``[rows_lo, rows_hi]``."""
    rs = _np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    period = 1.0 / float(rate)
    events = [{"t": round(i * period, 6),
               "rows": int(rs.randint(rows_lo, rows_hi + 1))}
              for i in range(n)]
    return Trace("serve", events,
                 meta={"dim": int(dim), "offered_rps": float(rate)},
                 seed=seed)


def synth_decode_trace(rate=12.0, seconds=3.0, vocab=48, prompt_lo=4,
                       prompt_hi=24, new_tokens=24, seed=5):
    """A synthetic decode-session schedule matching bench.py's
    ``--serve-decode`` open loop: sessions arrive on a fixed grid,
    each with a uniformly drawn prompt length."""
    rs = _np.random.RandomState(seed)
    n = max(1, int(rate * seconds))
    period = 1.0 / float(rate)
    events = [{"t": round(i * period, 6),
               "prompt_len": int(rs.randint(prompt_lo, prompt_hi + 1))}
              for i in range(n)]
    return Trace("decode", events,
                 meta={"vocab": int(vocab),
                       "new_tokens": int(new_tokens),
                       "offered_sessions_per_sec": float(rate)},
                 seed=seed)


def replay(trace, submit, budget_frac=1.0):
    """Drive *submit* through the trace's open-loop arrival grid from
    the calling thread.

    ``submit(payload, index)`` is called once per event, at (never
    before) its scheduled offset; the grid NEVER waits on the system
    under test.  Returns ``(records, wall_s)`` where each record is
    ``(slot_offset, t_submit, handle)`` — *handle* is whatever submit
    returned (a ServeFuture, a decode session, None for a shed
    admission), stamped with the monotonic submit time the latency
    accounting runs against."""
    payloads = trace.payloads(budget_frac)
    sched = trace.schedule(budget_frac)
    records = []
    t_start = time.monotonic()
    for i, ((offset, _size), payload) in enumerate(zip(sched,
                                                       payloads)):
        delay = (t_start + offset) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_sub = time.monotonic()
        records.append((offset, t_sub, submit(payload, i)))
    return records, time.monotonic() - t_start
