"""Scoped symbol attributes — public module surface (reference:
python/mxnet/attribute.py).  The implementation lives with the symbol
graph (``symbol/symbol.py``); ``with mx.attribute.AttrScope(
ctx_group='dev1'):`` tags every symbol created in scope, which is how
manual model-parallel groups are declared for ``group2ctx``."""

from __future__ import annotations

from .symbol.symbol import AttrScope

__all__ = ["AttrScope"]
