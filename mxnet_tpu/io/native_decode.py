"""ctypes binding for the native JPEG decode+augment worker team.

Reference capability: ``src/io/iter_image_recordio_2.cc:141-149`` — the
reference decodes and augments inside a C++ OMP team, so image
throughput scales with cores instead of paying a Python call per image.
``src/io/jpeg_decode_pool.cc`` is that team for this framework; one
``decode_batch`` call turns a list of encoded JPEG buffers into an
assembled (n, h, w, 3) uint8 RGB batch, with shorter-side resize,
center/seeded-random crop, and mirror done worker-side.

The pool covers the plain classification pipeline (resize + crop +
mirror, the ResNet config).  Color/PCA/aspect augmenters stay on the
cv2 path — ``ImageIter`` falls back automatically when they are
requested.
"""

from __future__ import annotations

import ctypes
import os

import numpy as _np

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "build", "libjpeg_decode_pool.so")

_lib = None


class _DecodeCfg(ctypes.Structure):
    _fields_ = [("resize", ctypes.c_int32),
                ("out_h", ctypes.c_int32),
                ("out_w", ctypes.c_int32),
                ("rand_crop", ctypes.c_int32),
                ("rand_mirror", ctypes.c_int32)]


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.MXIOPoolCreate.restype = ctypes.c_void_p
    lib.MXIOPoolCreate.argtypes = [ctypes.c_int]
    lib.MXIOPoolFree.argtypes = [ctypes.c_void_p]
    lib.MXIOPoolDecodeBatch.restype = ctypes.c_int
    lib.MXIOPoolDecodeBatch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int,
        ctypes.POINTER(_DecodeCfg),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return lib


def available():
    """True when the native library is built (make -C src/io)."""
    return _load() is not None


class NativeDecodePool:
    """A persistent decode worker team (one per iterator)."""

    def __init__(self, num_threads, out_hw, resize=0, rand_crop=False,
                 rand_mirror=False):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "libjpeg_decode_pool.so not built; run make -C src/io")
        self._lib = lib
        self._pool = lib.MXIOPoolCreate(int(num_threads))
        self._cfg = _DecodeCfg(int(resize), int(out_hw[0]),
                               int(out_hw[1]), int(bool(rand_crop)),
                               int(bool(rand_mirror)))

    def decode_batch(self, bufs):
        """list[bytes] -> ((n, h, w, 3) uint8 RGB, ok mask)."""
        n = len(bufs)
        h, w = self._cfg.out_h, self._cfg.out_w
        out = _np.empty((n, h, w, 3), _np.uint8)
        rcs = _np.zeros((n,), _np.int32)
        # per-image augment seeds come from numpy's GLOBAL stream so
        # np.random.seed(...) pins this path exactly like it pins the
        # cv2 augmenter chain
        seeds = _np.random.randint(1, 2 ** 63 - 1, size=n,
                                   dtype=_np.uint64)
        buf_arr = (ctypes.c_char_p * n)(*bufs)
        len_arr = (ctypes.c_size_t * n)(*[len(b) for b in bufs])
        rc = self._lib.MXIOPoolDecodeBatch(
            self._pool, buf_arr, len_arr, n, ctypes.byref(self._cfg),
            seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            rcs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError("MXIOPoolDecodeBatch rc=%d" % rc)
        return out, rcs == 0

    def close(self):
        if getattr(self, "_pool", None):
            self._lib.MXIOPoolFree(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
