"""Device-resident input pipeline — host↔device overlap.

``PrefetchingIter`` (io.py) overlaps host decode with host compute
only: every batch it hands out is still a HOST array, and the training
loop pays a synchronous ``jax.device_put`` inside the step loop (the
reference framework's ``iter_prefetcher.h`` has the same shape — its
prefetch thread stops at host memory).  :class:`DevicePrefetcher` goes
one layer lower: the background producer runs host decode **and** the
host→device transfer, parking finished batches in a depth-K ring of
device-resident buffers, so by the time the consumer asks for batch N
its bytes are already on the chip and the fused train step dispatches
with zero input-side host work (``device_put_elided_total`` counts the
transfers the step loop consequently skips — see
docs/perf_input_pipeline.md).

Placement modes:

* plain device (default / ``device=``): ``jax.device_put`` onto one
  device — the Module path; the executor's ``_place`` then elides its
  own put because the batch is already committed there;
* ``mesh=``/``spec=``: ``jax.device_put`` with a
  ``NamedSharding(mesh, spec)`` (default ``P('dp')``) — the
  ParallelTrainer path; ``_device_batch`` sees the matching sharding
  and skips its transfer, so sharded batches are free.

Everything threaded is built from the :mod:`..sanitizer` factories, so
``MXNET_SAN=all`` / ``pytest --graftsan`` audits the ring's locks and
producer thread like every other subsystem.  ``state_dict`` /
``load_state`` pass through :class:`PrefetchingIter`'s (epoch-start
inner state, batches consumed) accounting, so a mid-epoch checkpoint
taken through the wrapper resumes bit-exactly (the producer runs AHEAD
of the consumer; prefetched-but-unconsumed device batches belong to
the resumed run).
"""

from __future__ import annotations

from .io import DataBatch, PrefetchingIter
from ..ndarray import NDArray
from ..ndarray.ndarray import _already_placed, _DEVICE_PUT_ELIDED
from ..observability import metrics as _obs_metrics

__all__ = ["DevicePrefetcher", "maybe_wrap"]

# module-level instrument refs — observed once per consumed batch (the
# ndarray.py hot-path discipline: no registry lookup per step)
_INPUT_WAIT = _obs_metrics.histogram(
    "input_wait_seconds",
    "host time the training loop waited on the device-prefetch ring "
    "for its next batch (steady-state overlap keeps this near zero)")
_STEPS_STALLED = _obs_metrics.counter(
    "steps_input_stalled_total",
    "training steps that found the device-prefetch ring empty and had "
    "to wait on input (the input pipeline is the bottleneck)")
_RING_OCCUPANCY = _obs_metrics.gauge(
    "device_prefetch_ring_occupancy",
    "device-resident batches parked in the DevicePrefetcher ring when "
    "the consumer asked for one (0 = consumer outrunning the producer)")


class DevicePrefetcher(PrefetchingIter):
    """Wrap a ``DataIter``/``DataLoader``-style iterator so batches
    arrive **device-resident**.

    Parameters
    ----------
    iters : DataIter
        The host-side iterator to wrap (anything with the DataIter
        protocol; gluon DataLoaders can be adapted via NDArrayIter).
    depth : int
        Ring depth K: how many decoded-and-transferred batches may be
        in flight ahead of the consumer.  Device memory cost is
        depth × batch bytes; 2 hides decode behind compute, deeper
        rings ride out decode-time jitter.
    device : Context, str, or jax.Device, optional
        Placement target for plain (non-mesh) mode; defaults to the
        current context's device.
    mesh : jax.sharding.Mesh, optional
        When given, batches are placed with
        ``NamedSharding(mesh, spec)`` instead of a single device —
        hand a ``ParallelTrainer`` its ``trainer.mesh`` and
        ``fit_batch`` consumes the batch with zero transfers.
    spec : jax.sharding.PartitionSpec, optional
        Data sharding spec in mesh mode (default ``P('dp')`` — batch
        rows over the data-parallel axis).
    label_spec : PartitionSpec, optional
        Label sharding spec (defaults to *spec*).
    retry : dict, optional
        Passed through to :class:`PrefetchingIter` (transient inner
        iterator failures retried with jittered backoff).

    Sparse batches (CSR/row-sparse containers) pass through
    un-transferred — their carriers move at consumption like before.
    The ring buffers are never donated: the fused step's donation
    covers weights/optimizer state only, so a buffered batch can be
    replayed (chaos NaN-poisoning, monitors) safely.
    """

    def __init__(self, iters, depth=2, device=None, mesh=None, spec=None,
                 label_spec=None, rename_data=None, rename_label=None,
                 retry=None):
        # placement target resolved BEFORE the producer thread starts
        # (super().__init__ launches it)
        self._sharding = None
        self._label_sharding = None
        self._device = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = spec if spec is not None else P("dp")
            self._sharding = NamedSharding(mesh, spec)
            self._label_sharding = NamedSharding(
                mesh, label_spec if label_spec is not None else spec)
        else:
            self._device = self._resolve_device(device)
        super().__init__(iters, rename_data=rename_data,
                         rename_label=rename_label,
                         prefetch_depth=depth, retry=retry)

    @staticmethod
    def _resolve_device(device):
        from ..context import Context, current_context
        if device is None:
            return current_context().jax_device
        if isinstance(device, (Context, str)):
            return Context(device).jax_device
        return device        # a live jax.Device

    # -- producer-side placement ------------------------------------------
    def _put_array(self, arr, target):
        """One array → device-resident NDArray (runs on the producer
        thread).  Sparse containers (CSR/RSP carry aux tables the jit
        consumes at bind time) pass through untouched; an array the
        inner iterator already committed to the target skips the
        re-put (the elision the satellite counter tracks)."""
        import jax
        if isinstance(arr, NDArray):
            if getattr(arr, "_aux", None) is not None:
                return arr   # sparse: moved at consumption, as before
            data = arr._data
        else:
            data = arr       # numpy (or jax) array
        if self._sharding is None:
            if _already_placed(data, target):
                _DEVICE_PUT_ELIDED.inc()
                return arr if isinstance(arr, NDArray) else NDArray(data)
        elif isinstance(data, jax.Array) and \
                getattr(data, "sharding", None) == target:
            _DEVICE_PUT_ELIDED.inc()
            return arr if isinstance(arr, NDArray) else NDArray(data)
        return NDArray(jax.device_put(data, target))

    def _transform(self, batch):
        data_target = self._sharding if self._sharding is not None \
            else self._device
        label_target = self._label_sharding if self._label_sharding is \
            not None else self._device
        data = [self._put_array(a, data_target) for a in batch.data] \
            if batch.data else batch.data
        label = [self._put_array(a, label_target) for a in batch.label] \
            if batch.label else batch.label
        out = DataBatch(data=data, label=label, pad=batch.pad,
                        index=batch.index, bucket_key=batch.bucket_key,
                        provide_data=batch.provide_data,
                        provide_label=batch.provide_label)
        return out

    # -- consumer side (the ring-pop protocol itself lives in
    #    PrefetchingIter.next(); only the instruments differ) -------------
    def _note_occupancy(self, occupancy):
        # occupancy sampled per consumed batch; 0 = the step is about
        # to stall on input
        _RING_OCCUPANCY.set(occupancy)

    def _note_delivery(self, occupancy, wait_s):
        _INPUT_WAIT.observe(wait_s)
        if occupancy == 0:
            # a real batch arrived only after the consumer blocked on
            # an empty ring — this step was input-bound
            _STEPS_STALLED.inc()


def maybe_wrap(train_data, device_prefetch, device=None, mesh=None,
               decode_only=False):
    """Resolve the ``fit(device_prefetch=...)`` /
    ``MXNET_DEVICE_PREFETCH`` knob: returns ``(iterator, created)``
    where *created* says a wrapper was built here (the caller owns
    ``close()``-ing it when the loop ends).

    ``device_prefetch`` semantics: ``None`` → consult the env knob;
    ``True`` → default ring depth 2; an int → that ring depth;
    ``0``/``False`` → explicitly off (overrides the env knob).
    An iterator that is already a PrefetchingIter (DevicePrefetcher
    included) is never re-wrapped.

    ``decode_only=True`` wraps with a host-side
    :class:`PrefetchingIter` instead — for placements this layer
    cannot produce (a multi-host global batch belongs to
    ``host_local_to_global``): decode still overlaps compute, and the
    consumer keeps its own placement path without paying a wasted
    single-device transfer first.
    """
    if device_prefetch is None:
        from ..config import get_env
        device_prefetch = get_env("MXNET_DEVICE_PREFETCH")
    if not device_prefetch:
        return train_data, False
    depth = 2 if device_prefetch is True else int(device_prefetch)
    if decode_only:
        # any PrefetchingIter already overlaps decode — re-wrapping
        # would only stack a second producer thread
        if isinstance(train_data, PrefetchingIter):
            return train_data, False
        return PrefetchingIter(train_data, prefetch_depth=depth), True
    if isinstance(train_data, DevicePrefetcher):
        return train_data, False
    return DevicePrefetcher(train_data, depth=depth, device=device,
                            mesh=mesh), True
