"""mx.io — data iterators (reference: python/mxnet/io/)."""

from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MNISTIter, CSVIter, LibSVMIter)  # noqa

class ImageRecordIter(DataIter):  # placeholder replaced in image.py wiring
    pass
