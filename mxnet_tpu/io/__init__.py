"""mx.io — data iterators (reference: python/mxnet/io/)."""

from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, MNISTIter, CSVIter, LibSVMIter)  # noqa
from .device_prefetch import DevicePrefetcher  # noqa: F401
from .image_record import ImageRecordIter  # noqa: F401
