"""Data iterators.

Reference: ``python/mxnet/io/io.py`` (DataDesc:41, DataBatch:114,
DataIter:178, ResizeIter:280, PrefetchingIter:345, NDArrayIter) and the C++
iterators in ``src/io/`` (iter_mnist.cc, iter_csv.cc, iter_libsvm.cc).

TPU note: the pipeline's job is to keep the chip fed — iterators produce
host numpy batches and a background-thread prefetcher overlaps host decode
with device compute (the reference uses dmlc::ThreadedIter the same way,
iter_prefetcher.h).  Conversion to device arrays happens at consumption so
XLA's async transfer overlaps too.
"""

from __future__ import annotations

import collections
import gzip
import os
import queue
import struct
import threading
import time

import numpy as _np

from ..base import np_dtype
from .. import ndarray as nd
from .. import sanitizer as _san
from ..ndarray import NDArray
from ..observability import metrics as _obs_metrics

# module-level ref — sampled once per consumed batch
_PREFETCH_DEPTH = _obs_metrics.gauge(
    "prefetch_queue_depth",
    "batches buffered in the PrefetchingIter producer queue")

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "LibSVMIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Name + shape (+dtype/layout) of a data slot
    (reference: io.py DataDesc:41)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One mini-batch (reference: io.py DataBatch:114)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data] if self.data else None
        label_shapes = [l.shape for l in self.label] if self.label else None
        return "{}: data shapes: {} label shapes: {}".format(
            type(self).__name__, data_shapes, label_shapes)


class DataIter:
    """Iterator protocol (reference: io.py DataIter:178).

    Resumable position (resilience subsystem): ``state_dict()``
    captures the iterator's mid-epoch cursor — including any
    shuffle order already drawn — and ``load_state()`` restores it,
    so a preempted job's ``TrainJobState`` resumes the data pipeline
    at the exact next batch instead of silently replaying or
    skipping.  The base implementation handles stateless iterators;
    every stateful subclass in this module overrides both."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def state_dict(self):
        """Serializable (JSON-safe) resume position."""
        return {"type": type(self).__name__}

    def _check_state_type(self, state):
        got = state.get("type")
        if got is not None and got != type(self).__name__:
            raise ValueError(
                "data-iterator state was captured from %r but is being "
                "restored into %r — the resumed job must rebuild the "
                "same pipeline" % (got, type(self).__name__))

    def load_state(self, state):
        self._check_state_type(state)

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy array)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d)
                 for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = collections.OrderedDict()
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out[k] = _np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py NDArrayIter).

    Elastic partitioning (docs/resilience.md "Elastic training"):
    with ``num_parts > 1`` the iterator walks GLOBAL rounds of
    ``batch_size * num_parts`` samples and yields only this worker's
    ``part_index``-th slice of each round.  All workers share the
    permutation (pass the same ``shuffle_seed``), so the union of all
    parts covers each epoch index exactly once.  ``repartition()``
    changes the layout at a batch boundary — the global cursor is
    preserved, so a dist_sync job that shrinks or grows mid-epoch
    keeps exactly-once coverage, and a mid-epoch joiner restores a
    survivor's ``state_dict()`` and repartitions to its own slot."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", part_index=0, num_parts=1,
                 shuffle_seed=None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        # permutations come from a PRIVATE seeded stream (seed drawn
        # once from global np.random, so np.random.seed reproducibility
        # is preserved): a mid-epoch resume restores (seed, drawn) and
        # every LATER epoch's reset() re-draws in lockstep with the
        # uninterrupted run — global-np.random shuffles could restore
        # the current order but not realign the stream position.  An
        # explicit shuffle_seed makes the order REPRODUCIBLE ACROSS
        # WORKERS — the elastic-partition contract.
        if shuffle:
            self._shuffle_seed = (int(shuffle_seed)
                                  if shuffle_seed is not None
                                  else int(_np.random.randint(
                                      0, 2 ** 31 - 1)))
        else:
            self._shuffle_seed = None
        self._shuffle_drawn = 0
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        self.part_index = int(part_index)
        self.num_parts = max(1, int(num_parts))
        self._check_partition(self.part_index, self.num_parts)
        self.cursor = -self._round
        self.num_source = len(self.data)
        self._cache_data = None
        self.reset()

    @property
    def _round(self):
        """Samples one GLOBAL step consumes across all partitions."""
        return self.batch_size * self.num_parts

    def _check_partition(self, part_index, num_parts):
        if not 0 <= part_index < num_parts:
            raise ValueError("part_index %d not in [0, %d)"
                             % (part_index, num_parts))
        if num_parts > 1 and self.last_batch_handle not in ("pad",
                                                            "discard"):
            raise ValueError(
                "partitioned iteration supports last_batch_handle "
                "'pad' or 'discard', not %r" % self.last_batch_handle)
        if self.num_data < self.batch_size * num_parts:
            raise ValueError(
                "global batch (batch_size %d * num_parts %d) must not "
                "exceed the data size %d"
                % (self.batch_size, num_parts, self.num_data))

    def repartition(self, part_index, num_parts):
        """Re-shard at a batch boundary: this worker becomes slice
        *part_index* of *num_parts*.  The GLOBAL consumed cursor is
        preserved, so across a shrink/grow every remaining sample of
        the epoch is still consumed exactly once (all workers must
        repartition at the same global cursor — the membership
        snapshot of a completed sync round gives them that boundary)."""
        part_index, num_parts = int(part_index), int(num_parts)
        consumed = self.cursor + self._round
        self._check_partition(part_index, num_parts)
        self.part_index, self.num_parts = part_index, num_parts
        self.cursor = consumed - self._round
        self._cache_data = None

    set_partition = repartition

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def _reshuffle(self):
        rs = _np.random.RandomState([self._shuffle_seed,
                                     self._shuffle_drawn])
        self._shuffle_drawn += 1
        rs.shuffle(self.idx)

    def hard_reset(self):
        if self.shuffle:
            self._reshuffle()
        self.cursor = -self._round

    def reset(self):
        if self.shuffle:
            self._reshuffle()
        if self.last_batch_handle == "roll_over" and \
                self.num_data - self.batch_size < self.cursor < \
                self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor - self.num_data)
        else:
            self.cursor = -self._round

    def iter_next(self):
        self.cursor += self._round
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        if self.last_batch_handle == "discard" and \
                self.cursor + self._round > self.num_data:
            raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=self.getindex())

    def _sel(self):
        """The dataset indices of THIS worker's slice of the current
        global round: positions ``[part*b, (part+1)*b)`` of the round
        window starting at ``cursor``; a window past the end wraps to
        the epoch's start (the reference's pad-by-wrapping, extended
        to the partitioned layout — ``getpad()`` names how many of
        this worker's rows are wrap-padding)."""
        lo = self.cursor + self.part_index * self.batch_size
        hi = lo + self.batch_size
        if hi <= self.num_data:
            return self.idx[lo:hi]
        if lo >= self.num_data:
            wrap = _np.arange(lo - self.num_data, hi - self.num_data)
            return self.idx[wrap % self.num_data]
        return _np.concatenate(
            [self.idx[lo:],
             self.idx[_np.arange(hi - self.num_data) % self.num_data]])

    def _getdata(self, data_source):
        sel = self._sel()
        return [nd.array(v[sel], dtype=str(v[sel].dtype)
                         if v.dtype != _np.float64 else "float32")
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label) if self.label else []

    def getindex(self):
        """The GLOBAL dataset indices of this worker's current slice
        (elastic drills assert exactly-once epoch coverage from these;
        wrap-padded rows repeat indices — trim with getpad())."""
        if self.num_parts == 1:
            return None     # legacy contract: plain batches carry None
        return self._sel()

    def getpad(self):
        """How many TRAILING rows of this worker's slice are wrap
        padding (only the final global round of a 'pad' epoch)."""
        if self.last_batch_handle != "pad":
            return 0
        lo = self.cursor + self.part_index * self.batch_size
        hi = lo + self.batch_size
        if hi <= self.num_data:
            return 0
        return min(hi - self.num_data, self.batch_size)

    def state_dict(self):
        """Cursor + the epoch's shuffle order + the private shuffle
        stream position: restoring all three makes a mid-epoch resume
        replay the EXACT remaining batches AND keeps every later
        epoch's re-shuffle in lockstep with the uninterrupted run."""
        return {"type": type(self).__name__,
                "cursor": int(self.cursor),
                "idx": self.idx.tolist() if self.shuffle else None,
                "shuffle_seed": self._shuffle_seed,
                "shuffle_drawn": self._shuffle_drawn,
                "part_index": self.part_index,
                "num_parts": self.num_parts}

    def load_state(self, state):
        """Restore a captured position.  A mid-epoch JOINER restores a
        survivor's state (same permutation + global cursor + the
        survivor's partition layout), then calls ``repartition()``
        with its own slot — the post-resize stream is bit-reproducible
        from jobstate alone."""
        self._check_state_type(state)
        if state.get("idx") is not None:
            idx = _np.asarray(state["idx"], dtype=self.idx.dtype)
            if idx.shape != self.idx.shape:
                raise ValueError(
                    "restored shuffle order has %d indices, dataset "
                    "has %d" % (idx.shape[0], self.idx.shape[0]))
            self.idx = idx
        if state.get("shuffle_seed") is not None:
            self._shuffle_seed = int(state["shuffle_seed"])
            self._shuffle_drawn = int(state.get("shuffle_drawn", 0))
        if state.get("num_parts") is not None:
            part = int(state.get("part_index", 0))
            parts = int(state["num_parts"])
            self._check_partition(part, parts)
            self.part_index, self.num_parts = part, parts
        self.cursor = int(state["cursor"])
        self._cache_data = None


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches
    (reference: io.py ResizeIter:280)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def state_dict(self):
        return {"type": type(self).__name__, "cur": int(self.cur),
                "inner": self.data_iter.state_dict()}

    def load_state(self, state):
        self._check_state_type(state)
        self.cur = int(state["cur"])
        self.current_batch = None
        self.data_iter.load_state(state["inner"])


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference: io.py PrefetchingIter:345,
    C++ iter_prefetcher.h).

    Failure semantics (resilience subsystem): an exception in the
    producer thread travels to the consumer and is raised from
    ``next()`` ONCE; further ``next()`` calls see ``StopIteration``
    (never a hang on an empty queue whose producer is gone), and
    ``reset()`` fully restores the iterator.  The producer only ever
    blocks on the queue in a stop-aware loop, so ``reset()`` can always
    drain + join it — no deadlock regardless of where the producer was.
    An optional *retry* spec (kwargs for
    :func:`mxnet_tpu.resilience.retry.retry_call`) retries transient
    inner-iterator failures with jittered backoff before surfacing
    them."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, retry=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter == 1, "PrefetchingIter wraps one iterator"
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = iters[0].batch_size
        self._depth = prefetch_depth
        self._retry = dict(retry) if retry else None
        self._queue = None
        self._stop = None
        self._thread = None
        self._peek = None
        self.current_batch = None
        # resume bookkeeping: the inner iterator's state at epoch
        # start + how many batches the CONSUMER has taken.  The
        # producer thread runs AHEAD of the consumer, so the inner
        # iterator's live cursor is useless for resume — the pair
        # (epoch-start state, consumed count) is the exact position.
        self._consumed = 0
        self._epoch_state = self._inner_state()
        self._start()

    @property
    def provide_data(self):
        return self.iters[0].provide_data

    @property
    def provide_label(self):
        return self.iters[0].provide_label

    @staticmethod
    def _put(q, stop, item):
        """Stop-aware put: never blocks past a reset() request."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _next_inner(self):
        if self._retry:
            from ..resilience.retry import retry_call
            cfg = dict(self._retry)
            cfg.setdefault("retry_on", (Exception,))
            give_up = tuple(cfg.pop("give_up_on", ()))
            return retry_call(self.iters[0].next,
                              give_up_on=give_up + (StopIteration,),
                              **cfg)
        return self.iters[0].next()

    def _transform(self, batch):
        """Producer-side per-batch hook (runs on the prefetch thread,
        BEFORE the batch enters the ring).  The base class passes
        batches through; :class:`DevicePrefetcher` overrides it to run
        ``jax.device_put`` here so host decode AND the host→device
        transfer overlap device compute."""
        return batch

    def _producer(self, q, stop):
        # q/stop are bound per-thread: a producer abandoned by reset()
        # keeps talking to ITS queue and stop event, never the
        # replacement epoch's
        while not stop.is_set():
            try:
                batch = self._transform(self._next_inner())
            except StopIteration:
                self._put(q, stop, None)
                return
            except Exception as e:  # exception travels to consumer
                self._put(q, stop, e)
                # trailing sentinel: after the consumer raises the
                # exception, further next() calls end the epoch
                # instead of hanging on a dead producer
                self._put(q, stop, None)
                return
            if not self._put(q, stop, batch):
                return

    def _start(self):
        self._closed = False
        self._queue = _san.queue(maxsize=self._depth)
        self._stop = _san.event()
        self._thread = _san.thread(
            target=self._producer, args=(self._queue, self._stop),
            daemon=True)
        self._thread.start()

    def _inner_state(self):
        sd = getattr(self.iters[0], "state_dict", None)
        return sd() if sd is not None else None

    def _stop_producer(self):
        import logging
        import time as _time
        self._stop.set()
        # drain-then-join until the producer exits: it can only block
        # in the stop-aware _put, so freeing queue slots always
        # unwedges it (a producer mid-put refills what we drain, hence
        # the loop rather than a single drain).  Bounded: a producer
        # wedged inside the INNER iterator's next() is abandoned — the
        # fresh queue started next detaches it either way
        deadline = _time.monotonic() + 10.0
        while self._thread is not None and self._thread.is_alive():
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
            if _time.monotonic() > deadline:
                logging.getLogger(__name__).warning(
                    "PrefetchingIter: producer thread did not exit "
                    "within 10s (inner iterator wedged?); detaching it")
                break

    def reset(self):
        self._stop_producer()
        self.iters[0].reset()
        self._peek = None
        self.current_batch = None
        self._consumed = 0
        self._epoch_state = self._inner_state()
        self._start()

    def close(self):
        """Stop the producer thread and drop buffered batches (a ring
        of device-resident buffers holds depth×batch bytes of device
        memory until released).  The iterator stays resumable:
        ``reset()`` or ``load_state()`` starts a fresh producer."""
        self._stop_producer()
        self._closed = True
        self._peek = None
        self.current_batch = None

    def state_dict(self):
        """Pass-through position: the inner iterator's state at epoch
        start plus the number of batches actually DELIVERED to the
        consumer (prefetched-but-unconsumed batches belong to the
        resumed run, not this one)."""
        return {"type": type(self).__name__,
                "epoch_start": self._epoch_state,
                "consumed": self._consumed}

    def load_state(self, state):
        self._check_state_type(state)
        if state.get("epoch_start") is None:
            raise ValueError(
                "PrefetchingIter state is not resumable: the wrapped "
                "iterator (%s) has no state_dict()"
                % type(self.iters[0]).__name__)
        self._stop_producer()
        inner = self.iters[0]
        inner.load_state(state["epoch_start"])
        # fast-forward through the already-consumed batches on the
        # CALLER's thread (deterministic inner iterators re-decode the
        # skipped range; no producer races with the skipping)
        consumed = int(state["consumed"])
        for _ in range(consumed):
            inner.next()
        self._peek = None
        self.current_batch = None
        self._consumed = consumed
        self._epoch_state = state["epoch_start"]
        self._start()

    def repartition(self, part_index, num_parts):
        """Elastic re-shard THROUGH the prefetch ring: the producer
        runs ahead of the consumer, so simply delegating would either
        skip the prefetched-but-undelivered batches or replay ones
        already handed out.  Instead the inner iterator is rewound to
        the exact delivered position (epoch-start state + consumed
        fast-forward, the same protocol as :meth:`load_state`),
        repartitioned there, and a fresh producer started — no sample
        is lost or duplicated across the resize."""
        inner = self.iters[0]
        rp = getattr(inner, "repartition", None)
        if rp is None:
            raise AttributeError(
                "wrapped iterator %s has no repartition()"
                % type(inner).__name__)
        if self._epoch_state is None:
            raise ValueError(
                "cannot repartition through %s: the wrapped iterator "
                "(%s) has no state_dict()" % (
                    type(self).__name__, type(inner).__name__))
        self._stop_producer()
        inner.load_state(self._epoch_state)
        for _ in range(self._consumed):
            inner.next()
        rp(part_index, num_parts)
        self._peek = None
        self.current_batch = None
        self._consumed = 0
        self._epoch_state = self._inner_state()
        self._start()

    def _note_occupancy(self, occupancy):
        """Consumer-side hook, called with the ring occupancy right
        before popping (0 = the consumer is about to block on input).
        Subclasses override to feed their own instruments."""
        _PREFETCH_DEPTH.set(occupancy)

    def _note_delivery(self, occupancy, wait_s):
        """Consumer-side hook, called after a REAL batch (not the
        end-of-epoch sentinel or a producer exception) was popped:
        *wait_s* is how long the consumer blocked on the ring."""

    def next(self):
        if self._peek is not None:
            batch, self._peek = self._peek, None
            self.current_batch = batch
            return batch
        if self._closed:
            # the drained queue has no producer — blocking on it would
            # hang forever, so fail loudly instead
            raise RuntimeError(
                "%s.next() after close(): the producer is stopped and "
                "the ring drained; reset() or load_state() starts a "
                "fresh producer" % type(self).__name__)
        occupancy = self._queue.qsize()
        self._note_occupancy(occupancy)
        t0 = time.perf_counter()
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        self._note_delivery(occupancy, time.perf_counter() - t0)
        self._consumed += 1
        self.current_batch = item
        return item

    def iter_next(self):
        """Peek semantics: a True return makes the batch available via
        getdata/getlabel AND the next next() call (no batch is dropped)."""
        if self._peek is not None:
            return True
        try:
            batch = self.next()  # sets current_batch
        except StopIteration:
            return False
        self._peek = batch  # next() will return this same batch
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class MNISTIter(DataIter):
    """idx-ubyte MNIST reader (reference: src/io/iter_mnist.cc:260)."""

    def __init__(self, image, label, batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=0, input_shape=None,
                 **kwargs):
        data, labels = _read_idx_images(image), _read_idx_labels(label)
        if flat:
            data = data.reshape(data.shape[0], -1)
        else:
            data = data.reshape(data.shape[0], 1, data.shape[1],
                                data.shape[2])
        if input_shape is not None:
            data = data.reshape((data.shape[0],) + tuple(input_shape))
        data = data.astype(_np.float32) / 255.0
        self._inner = NDArrayIter(data, labels.astype(_np.float32),
                                  batch_size=batch_size, shuffle=shuffle,
                                  last_batch_handle="discard")
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def state_dict(self):
        return {"type": type(self).__name__,
                "inner": self._inner.state_dict()}

    def load_state(self, state):
        self._check_state_type(state)
        self._inner.load_state(state["inner"])


def _open_maybe_gz(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_idx_images(path):
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, "bad idx image magic in %s" % path
        buf = f.read(n * rows * cols)
        return _np.frombuffer(buf, dtype=_np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, "bad idx label magic in %s" % path
        return _np.frombuffer(f.read(n), dtype=_np.uint8)


class CSVIter(DataIter):
    """Dense CSV reader (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",",
                           dtype=_np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",",
                                dtype=_np.float32, ndmin=1)
        else:
            label = _np.zeros((data.shape[0],), _np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard")
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def state_dict(self):
        return {"type": type(self).__name__,
                "inner": self._inner.state_dict()}

    def load_state(self, state):
        self._check_state_type(state)
        self._inner.load_state(state["inner"])


class LibSVMIter(DataIter):
    """Sparse LibSVM reader producing CSR batches
    (reference: src/io/iter_libsvm.cc — feeds example/sparse)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, **kwargs):
        num_features = data_shape[0] if isinstance(data_shape,
                                                   (tuple, list)) \
            else data_shape
        labels = []
        indptr = [0]
        indices = []
        values = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        self._values = _np.asarray(values, _np.float32)
        self._indices = _np.asarray(indices, _np.int32)
        self._indptr = _np.asarray(indptr, _np.int32)
        self._labels = _np.asarray(labels, _np.float32)
        self._num_features = num_features
        self.batch_size = batch_size
        self._num = len(labels)
        self._cursor = 0
        self._round = round_batch
        super().__init__(batch_size)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        from ..ndarray import sparse as _sp
        if self._cursor >= self._num:
            raise StopIteration
        lo = self._cursor
        hi = lo + self.batch_size
        pad = 0
        if hi > self._num:
            if not self._round:
                raise StopIteration
            pad = hi - self._num  # wrap the final batch (reference
            # round_batch semantics, iter_libsvm.cc)
        self._cursor = hi
        rows = [(r % self._num) for r in range(lo, hi)]
        values, indices, indptr = [], [], [0]
        for r in rows:
            s, e = self._indptr[r], self._indptr[r + 1]
            values.append(self._values[s:e])
            indices.append(self._indices[s:e])
            indptr.append(indptr[-1] + (e - s))
        batch = _sp.csr_matrix(
            (_np.concatenate(values) if values else
             _np.zeros(0, _np.float32),
             _np.concatenate(indices) if indices else
             _np.zeros(0, _np.int32),
             _np.asarray(indptr, _np.int32)),
            shape=(self.batch_size, self._num_features))
        label = nd.array(self._labels[[r for r in rows]])
        return DataBatch(data=[batch], label=[label], pad=pad)

    def iter_next(self):
        if self._round:
            return self._cursor < self._num
        return self._cursor + self.batch_size <= self._num

    def state_dict(self):
        return {"type": type(self).__name__,
                "cursor": int(self._cursor)}

    def load_state(self, state):
        self._check_state_type(state)
        self._cursor = int(state["cursor"])
