"""ImageRecordIter — the ImageNet hot path.

Reference capability: `src/io/iter_image_recordio_2.cc:78-149`
(RecordIO chunks -> OMP-parallel JPEG decode + augment -> inline batch
assembly) behind `MXNET_REGISTER_IO_ITER(ImageRecordIter)`.  The
TPU-native equivalent: `mx.image.ImageIter` decodes + augments on a
cv2 thread pool (the GIL is released inside OpenCV, so threads scale
like the reference's OMP team) and `PrefetchingIter` double-buffers
assembled batches so the accelerator never waits on the host.
"""

from __future__ import annotations

import os

import numpy as _np

from .io import DataIter, PrefetchingIter


def ImageRecordIter(path_imgrec, data_shape, batch_size,
                    path_imgidx=None, label_width=1, shuffle=False,
                    rand_crop=False, rand_mirror=False, resize=0,
                    rand_resize=False, mean_r=0.0, mean_g=0.0,
                    mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                    max_random_brightness=0.0, max_random_contrast=0.0,
                    max_random_saturation=0.0, max_random_hue=0.0,
                    random_gray_prob=0.0, pca_noise=0.0,
                    preprocess_threads=None, prefetch_buffer=4,
                    data_name="data", label_name="softmax_label",
                    **kwargs):
    """Build the parallel record->batch pipeline.  Accepts the
    reference's flat parameter names (mean_r/std_r etc.,
    image_aug_default.cc) and returns a prefetching DataIter."""
    from ..image import CreateAugmenter, ImageIter

    mean = None
    if mean_r or mean_g or mean_b:
        mean = _np.array([mean_r, mean_g, mean_b], _np.float32)
    std = None
    if std_r != 1.0 or std_g != 1.0 or std_b != 1.0:
        std = _np.array([std_r, std_g, std_b], _np.float32)
    augs = CreateAugmenter(
        data_shape, resize=resize, rand_crop=rand_crop,
        rand_resize=rand_resize, rand_mirror=rand_mirror, mean=mean,
        std=std, brightness=max_random_brightness,
        contrast=max_random_contrast,
        saturation=max_random_saturation, hue=max_random_hue,
        pca_noise=pca_noise, rand_gray=random_gray_prob)
    # plain classification configs (resize + crop + mirror + mean/std,
    # no color/aspect augmentation) take the native libjpeg team —
    # the reference's OMP decode path (iter_image_recordio_2.cc:141);
    # anything fancier stays on the cv2 augmenter chain
    native = None
    if os.environ.get("MXNET_TPU_NATIVE_DECODE", "1") != "0" and \
            not (rand_resize or max_random_brightness
                 or max_random_contrast or max_random_saturation
                 or max_random_hue or random_gray_prob or pca_noise):
        native = {"resize": int(resize or 0), "rand_crop": rand_crop,
                  "rand_mirror": rand_mirror, "mean": mean, "std": std}
    inner = ImageIter(
        batch_size=batch_size, data_shape=data_shape,
        label_width=label_width, path_imgrec=path_imgrec,
        path_imgidx=path_imgidx, shuffle=shuffle, aug_list=augs,
        data_name=data_name, label_name=label_name,
        num_threads=preprocess_threads or
        max(1, (os.cpu_count() or 2) // 2),
        native_pipeline=native)
    return PrefetchingIter(inner, prefetch_depth=prefetch_buffer)
