"""Jittered-exponential-backoff retry with a deadline.

The reference framework leaned on ps-lite's resender/heartbeats for
transient-failure masking; this rebuild's host-side IO paths (weight
store reads, dataloader worker respawn, prefetch recovery) use this
one retry policy instead of ad-hoc loops.

Everything time-related is injectable (``sleep``, ``clock``, ``rng``)
so tests — and the CI chaos drills — run deterministic backoff
schedules with zero real sleeping.
"""

from __future__ import annotations

import functools
import logging
import random
import time

__all__ = ["retry", "retry_call"]

log = logging.getLogger(__name__)


def backoff_delays(attempts, base_delay, max_delay, multiplier, jitter,
                   rng):
    """The delay after attempt i (1-based): capped exponential with
    multiplicative jitter in ``[1 - jitter, 1]`` — jitter decorrelates
    a fleet of workers hammering the same recovering resource."""
    for i in range(1, attempts):
        delay = min(max_delay, base_delay * multiplier ** (i - 1))
        if jitter:
            delay *= 1.0 - jitter * rng.random()
        yield delay


def retry_call(fn, args=(), kwargs=None, *, attempts=5, base_delay=0.05,
               max_delay=2.0, multiplier=2.0, jitter=0.5, deadline=None,
               retry_on=(OSError,), give_up_on=(), sleep=time.sleep,
               clock=time.monotonic, rng=None, logger=None, on_retry=None):
    """Call ``fn(*args, **kwargs)``, retrying on *retry_on* exceptions.

    *give_up_on* exceptions propagate immediately even when they
    subclass a *retry_on* type (e.g. ``FileNotFoundError`` under
    ``OSError``: a missing file is not transient).  *deadline* bounds
    the TOTAL time budget: a retry whose backoff would overrun it
    re-raises instead of sleeping.  The last exception always
    propagates unwrapped — callers keep their except clauses.
    """
    kwargs = kwargs or {}
    rng = rng if rng is not None else random.Random()
    delays = backoff_delays(attempts, base_delay, max_delay, multiplier,
                            jitter, rng)
    lg = logger or log
    start = clock()
    attempt = 1
    while True:
        try:
            return fn(*args, **kwargs)
        except give_up_on:
            raise
        except retry_on as exc:
            if attempt >= attempts:
                raise
            delay = next(delays)
            if deadline is not None and \
                    (clock() - start) + delay > deadline:
                lg.debug("retry: deadline %.3fs would be exceeded; "
                         "giving up after attempt %d (%s)", deadline,
                         attempt, exc)
                raise
            lg.debug("retry: attempt %d/%d failed (%s: %s); backing off "
                     "%.3fs", attempt, attempts, type(exc).__name__, exc,
                     delay)
            from ..observability import events as _obs_events
            from ..observability import metrics as _metrics
            _metrics.counter(
                "retry_attempts_total",
                "retried (failed-then-backed-off) attempts across "
                "every retry_call site").inc()
            _obs_events.emit("retry", fn=getattr(fn, "__name__",
                                                 repr(fn)[:80]),
                             attempt=attempt, of=attempts,
                             error="%s: %s" % (type(exc).__name__,
                                               str(exc)[:200]),
                             backoff_s=round(delay, 4))
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)
            attempt += 1


def retry(**cfg):
    """Decorator form of :func:`retry_call`::

        @retry(attempts=4, retry_on=(OSError,),
               give_up_on=(FileNotFoundError,))
        def read_weights(path): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, args, kwargs, **cfg)
        wrapper.retry_config = dict(cfg)
        return wrapper
    return deco
