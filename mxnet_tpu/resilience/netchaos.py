"""Deterministic network fault injection for the distributed KVStore.

The chaos harness (:mod:`~mxnet_tpu.resilience.chaos`) covers process
and filesystem faults; this module covers the layer most likely to
fail in a fleet — the network.  The injection points are consulted by
the PRODUCTION socket choke points in ``_kvstore_impl``
(:func:`_rpc_call` worker-side, the server's reply path and PUSH
handler), so a chaos-enabled drill drives the exact retry / dedup /
snapshot-restore / eviction code a real outage exercises.

Everything rides the same counter-based ``MXNET_CHAOS`` spec (or
programmatic ``chaos.configure``): each injection is an integer budget
consumed in call order, so a drill armed with ``net_drop_reply=2``
fires on exactly the first two eligible replies and never again.  No
randomness; the only sleeps are the injected delays themselves.

Spec keys (all integers):

``net_partition=N``
    Worker: the next N bulk RPC sends raise ``ConnectionError``
    before any bytes move (transient partition); the transport's
    retry path reconnects and resends the same request id.
``net_delay_request=N`` / ``net_delay_ms=X``
    Worker: delay the next N sends by X milliseconds (default 200).
``net_dup_request=N``
    Worker: send the next N bulk requests TWICE back-to-back with the
    same ``(rank, seq)`` id — the server's dedup window must apply
    the mutation exactly once and answer the duplicate from cache.
``net_torn_request=N``
    Worker: send only half the frame, then close the socket (the
    server sees EOF mid-frame); the retry path reconnects.
``net_drop_reply=N``
    Server: compute the reply — the state mutation has already
    happened — then drop it.  The worker's RPC timeout fires and the
    retried request id must dedup, not double-apply.
``net_delay_reply=N`` / ``net_delay_ms=X``
    Server: delay the next N replies by X milliseconds.  A delay
    longer than the worker's ``MXNET_KVSTORE_RPC_TIMEOUT`` forces the
    full timeout → reconnect → retry → dedup path.
``net_torn_reply=N``
    Server: send half the reply, then close the connection.
``net_kill_server_at=K``
    Server: hard-exit the process (``os._exit(137)``, no cleanup —
    like SIGKILL) on the K-th PUSH received, BEFORE applying it.  The
    restarted server must restore its state snapshot and the workers'
    retried pushes must apply exactly once against the committed
    lineage.

See docs/resilience.md ("Distributed fault tolerance") for the drill
that exercises every class: ``ci/netchaos_drill.py``.
"""

from __future__ import annotations

import logging
import os
import time

from . import chaos

__all__ = ["on_worker_send", "on_server_reply", "on_server_push",
           "DEFAULT_DELAY_MS"]

log = logging.getLogger(__name__)

DEFAULT_DELAY_MS = 200

# patchable seam: os._exit is untestable in-process, and the kill
# injection must stay unit-testable
_exit = os._exit


def _delay_seconds():
    return chaos.active().get("net_delay_ms", DEFAULT_DELAY_MS) / 1000.0


def on_worker_send(kind):
    """Worker-side fault point, consulted before a bulk RPC's bytes
    move.  May raise ``ConnectionError`` (partition) or sleep
    (delay); returns directives the transport applies itself:
    ``{'torn': bool, 'dup': bool}`` (empty dict when idle)."""
    if not chaos.enabled():
        return {}
    if chaos.consume("net_partition"):
        log.warning("netchaos: injected partition on RPC kind %d", kind)
        raise ConnectionError("netchaos: injected network partition")
    if chaos.consume("net_delay_request"):
        time.sleep(_delay_seconds())
    out = {}
    if chaos.consume("net_torn_request"):
        log.warning("netchaos: tearing request frame (kind %d)", kind)
        out["torn"] = True
    if chaos.consume("net_dup_request"):
        log.warning("netchaos: duplicating request (kind %d)", kind)
        out["dup"] = True
    return out


def on_server_reply(kind):
    """Server-side fault point for a computed reply: returns
    ``'drop'``, ``'torn'``, or ``None`` (after an optional injected
    delay).  The state mutation already happened — these faults
    target the reply path, which is exactly where exactly-once
    semantics get hard."""
    if not chaos.enabled():
        return None
    if chaos.consume("net_drop_reply"):
        log.warning("netchaos: dropping reply to RPC kind %d", kind)
        return "drop"
    if chaos.consume("net_delay_reply"):
        time.sleep(_delay_seconds())
    if chaos.consume("net_torn_reply"):
        log.warning("netchaos: tearing reply to RPC kind %d", kind)
        return "torn"
    return None


def on_server_push():
    """Hard-kill switch consulted by the server's PUSH handler before
    the push is registered or applied: ``net_kill_server_at=K`` exits
    the process on the K-th PUSH received.  No cleanup runs (same as
    SIGKILL), so recovery is entirely the restarted server's snapshot
    restore plus the workers' request-id retries."""
    if not chaos.enabled():
        return
    k = chaos.active().get("net_kill_server_at")
    if not k:
        return
    n = chaos.tick("netchaos_push")
    if n == k:
        log.warning("netchaos: hard-killing server process at push %d", n)
        from ..observability import events as _obs_events
        from ..observability import metrics as _metrics
        _metrics.counter("chaos_injections_total",
                         "chaos faults actually fired").inc()
        _obs_events.emit("chaos", injection="net_kill_server_at",
                         fire=1, budget=1)
        _exit(137)
