"""Crash-safe checkpointing: atomic writes + a checksum manifest.

The reference framework could rely on ps-lite server replication and a
C++ engine that was never half-killed mid-write; a preemptible TPU job
has neither, so every persisted artifact here follows one rule: **a
path either holds the complete old bytes or the complete new bytes,
never a mixture**, and the manifest — itself written atomically, and
always LAST — is the single commit point.  A kill at any instruction
leaves the previous checkpoint fully restorable.

Layout::

    <prefix>-NNNN-symbol.json     graph, per epoch (manifest-tracked)
    <prefix>-NNNN.params          tensors  (``arg:<n>`` / ``aux:<n>``)
    <prefix>-NNNN.states          optimizer state (legacy Updater bytes)
    <prefix>-NNNN.jobstate.json   TrainJobState (mid-epoch resume)
    <prefix>.manifest.json        commit ledger (written last)
    <prefix>-symbol.json          convenience copy at the reference's
                                  legacy name (NOT manifest-tracked)

Every manifest entry references only its OWN files — a shared symbol
file would let epoch N's save invalidate epoch N-1's checksums in the
crash window before the commit.  The legacy ``<prefix>-symbol.json``
name the reference's loaders expect is maintained as a last-write-wins
convenience copy outside the integrity guarantee;
``CheckpointRecord.load`` always reads the verified per-epoch file.

Manifest format (version 1)::

    {"version": 1,
     "checkpoints": [
       {"epoch": 3,
        "files": {"run-0003.params": {"sha256": "...", "size": 1234},
                  "run-symbol.json": {"sha256": "...", "size": 567}}},
       ...newest last...
     ]}

Checksums are computed over the exact in-memory bytes handed to the
atomic writer, so any later divergence on disk (torn write, bit rot,
truncation) is detected by :meth:`CheckpointManager.restore_latest`,
which walks newest→oldest and returns the first fully-intact entry.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import threading

from . import chaos
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _metrics

__all__ = ["atomic_write", "atomic_write_stream", "fsync_dir",
           "CheckpointManager", "CheckpointRecord", "MANIFEST_VERSION"]

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1


def fsync_dir(dirname):
    """Best-effort fsync of a directory so a rename survives power
    loss (no-op on platforms without directory fds)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path, data, fsync=True):
    """Write *data* (bytes) to *path* atomically: tmp file in the same
    directory + flush + fsync + ``os.replace`` + directory fsync.  A
    crash at ANY point leaves either the old complete file or the new
    complete file at *path* — never a torn mixture (a stale ``.tmp.*``
    sibling at worst, which the next write of the same path replaces).
    """
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise TypeError("atomic_write expects bytes, got %s"
                        % type(data).__name__)
    chaos.on_file_write(path)
    # pid + per-process sequence: concurrent writers of the SAME path
    # (background checkpoint thread vs a foreground save) must never
    # share a tmp file, or the replace could promote interleaved bytes
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), next(_TMP_SEQ))
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        chaos.on_pre_replace(path)
        os.replace(tmp, path)
    except Exception:
        # transient failure (not a simulated kill, which subclasses
        # BaseException and must leave the tmp behind like a real one):
        # don't litter the directory
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_dir(os.path.dirname(path))
    chaos.on_post_replace(path)


def atomic_write_stream(path, writer, fsync=True):
    """Like :func:`atomic_write`, but *writer(fileobj)* streams the
    payload into the tmp file — for serializers (``np.savez``) whose
    output would otherwise have to be materialized in memory first.
    Same crash guarantee, same chaos injection points."""
    chaos.on_file_write(path)
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), next(_TMP_SEQ))
    try:
        with open(tmp, "wb") as f:
            writer(f)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        chaos.on_pre_replace(path)
        os.replace(tmp, path)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_dir(os.path.dirname(path))
    chaos.on_post_replace(path)


_TMP_SEQ = itertools.count()

# one commit lock per manifest path, shared across CheckpointManager
# instances in this process: two managers on the same prefix must not
# interleave their manifest read-modify-write (cross-PROCESS writers
# are out of scope — run one trainer per prefix)
_COMMIT_LOCKS = {}
_COMMIT_LOCKS_GUARD = _san.lock(label="checkpoint._COMMIT_LOCKS_GUARD")


def _commit_lock(manifest_path):
    key = os.path.abspath(manifest_path)
    with _COMMIT_LOCKS_GUARD:
        lock = _COMMIT_LOCKS.get(key)
        if lock is None:
            lock = _COMMIT_LOCKS[key] = _san.lock(
                label="checkpoint.commit:" + key)
        return lock


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


class CheckpointRecord:
    """One intact checkpoint as returned by
    :meth:`CheckpointManager.restore_latest` — verified paths plus a
    loader."""

    __slots__ = ("epoch", "dirname", "files")

    def __init__(self, epoch, dirname, files):
        self.epoch = epoch
        self.dirname = dirname
        self.files = dict(files)        # basename -> verified abs path

    def _path_with_suffix(self, suffix):
        for name, path in self.files.items():
            if name.endswith(suffix):
                return path
        return None

    @property
    def symbol_path(self):
        return self._path_with_suffix("-symbol.json")

    @property
    def params_path(self):
        return self._path_with_suffix(".params")

    @property
    def states_path(self):
        return self._path_with_suffix(".states")

    @property
    def jobstate_path(self):
        return self._path_with_suffix(".jobstate.json")

    def load_job_state(self):
        """The :class:`~mxnet_tpu.resilience.jobstate.TrainJobState`
        stored with this checkpoint, or None for a params-only entry
        (pre-job-state checkpoints resume at the epoch boundary)."""
        path = self.jobstate_path
        if path is None:
            return None
        from .jobstate import TrainJobState
        with open(path, "rb") as f:
            return TrainJobState.from_bytes(f.read())

    def load(self):
        """Deserialize to ``(symbol_or_None, arg_params, aux_params)``
        — same split as ``model.load_checkpoint``."""
        from ..ndarray import utils as nd_utils
        symbol = None
        if self.symbol_path is not None:
            from .. import symbol as sym_mod
            symbol = sym_mod.load(self.symbol_path)
        from ..model import _split_save_dict
        arg_params, aux_params = _split_save_dict(
            nd_utils.load(self.params_path), context="checkpoint %r"
            % self.params_path)
        return symbol, arg_params, aux_params

    def __repr__(self):
        return "CheckpointRecord(epoch=%d, files=%s)" % (
            self.epoch, sorted(self.files))


class CheckpointManager:
    """Crash-safe checkpoint store for one ``prefix``.

    * every file goes through :func:`atomic_write`;
    * the manifest is updated last (the commit point) and carries
      per-file sha256 + size;
    * ``keep_last=K`` rotates old epochs out, deleting files no
      remaining entry references (the shared symbol file survives);
    * ``background=True`` (or per-call) serializes synchronously —
      the caller may mutate parameters right after — and performs the
      writes + commit on a daemon thread; :meth:`wait` joins and
      re-raises any background failure.
    """

    def __init__(self, prefix, keep_last=None, background=False,
                 logger=None):
        self.prefix = prefix
        if keep_last is None:
            from ..config import get_env
            keep_last = get_env("MXNET_CHECKPOINT_KEEP_LAST")
        self.keep_last = int(keep_last or 0)       # 0 = keep everything
        self.background = background
        self.logger = logger or log
        # write+commit section — shared per manifest path across
        # manager instances in this process
        self._lock = _commit_lock(prefix + ".manifest.json")
        # _plock (leaf — never held across a join or a write+commit)
        # guards the background bookkeeping: two background saves, or a
        # save racing wait(), otherwise lose threads from _pending via
        # the filter-then-reassign below (found by graftsched's
        # checkpoint scenario: the un-joined writer commits after
        # wait() returned)
        self._plock = _san.lock(label="checkpoint.pending")
        self._pending = []                         # background threads
        self._bg_error = None
        _san.track(self, ("_pending", "_bg_error"),
                   label="CheckpointManager")

    @property
    def manifest_path(self):
        return self.prefix + ".manifest.json"

    @property
    def dirname(self):
        return os.path.dirname(os.path.abspath(self.prefix))

    @property
    def basename(self):
        return os.path.basename(self.prefix)

    # -- manifest ----------------------------------------------------------
    def _read_manifest(self):
        path = self.manifest_path
        if not os.path.exists(path):
            return {"version": MANIFEST_VERSION, "checkpoints": []}
        try:
            with open(path, encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError) as exc:
            # the manifest is written atomically, so a torn one means
            # external meddling — treat as empty but say so
            self.logger.warning(
                "checkpoint manifest %s is unreadable (%s); treating as "
                "empty", path, exc)
            return {"version": MANIFEST_VERSION, "checkpoints": []}
        man.setdefault("checkpoints", [])
        return man

    def epochs(self):
        """Committed epochs, oldest first (no integrity check)."""
        return [e["epoch"] for e in self._read_manifest()["checkpoints"]]

    # -- saving ------------------------------------------------------------
    def save_checkpoint(self, epoch, symbol=None, arg_params=None,
                        aux_params=None, optimizer_states=None,
                        background=None, job_state=None):
        """Persist one checkpoint.  Serialization happens before this
        returns (the caller may keep training and mutating parameters);
        with *background*, the disk writes + manifest commit run on a
        daemon thread.  *job_state* (a
        :class:`~mxnet_tpu.resilience.jobstate.TrainJobState` or raw
        bytes) rides along as one more manifest-tracked file, so a
        mid-epoch resume is covered by the same checksum commit as the
        params it belongs to."""
        self._raise_pending()
        from ..ndarray import utils as nd_utils
        files = {}
        if job_state is not None:
            data = job_state.to_bytes() \
                if hasattr(job_state, "to_bytes") else bytes(job_state)
            files["%s-%04d.jobstate.json" % (self.basename, epoch)] = data
        if symbol is not None:
            # per-epoch symbol file: every manifest entry stays
            # self-contained (see module docstring)
            files["%s-%04d-symbol.json" % (self.basename, epoch)] = \
                symbol.tojson().encode("utf-8")
        save_dict = {("arg:%s" % k): v
                     for k, v in (arg_params or {}).items()}
        save_dict.update({("aux:%s" % k): v
                          for k, v in (aux_params or {}).items()})
        files["%s-%04d.params" % (self.basename, epoch)] = \
            nd_utils.save_bytes(save_dict)
        if optimizer_states is not None:
            files["%s-%04d.states" % (self.basename, epoch)] = \
                bytes(optimizer_states)
        entry = {"epoch": int(epoch),
                 "files": {name: {"sha256": _sha256(data),
                                  "size": len(data)}
                           for name, data in files.items()}}
        if background is None:
            background = self.background
        if background:
            t = _san.thread(target=self._write_and_commit_guarded,
                            args=(files, entry), daemon=True)
            with self._plock:
                self._pending = [p for p in self._pending
                                 if p.is_alive()]
                self._pending.append(t)
            t.start()
        else:
            self._write_and_commit(files, entry)
        return entry

    def save_module(self, module, epoch, save_optimizer_states=True,
                    background=None, job_state=None):
        """Checkpoint a bound Module (params + aux + optimizer state
        when available, plus an optional ``TrainJobState``) through
        this manager."""
        arg_params, aux_params = module.get_params()
        states = None
        if save_optimizer_states and \
                getattr(module, "optimizer_initialized", False):
            get_bytes = getattr(module, "_optimizer_states_bytes", None)
            if get_bytes is not None:
                states = get_bytes()
        return self.save_checkpoint(
            epoch, symbol=getattr(module, "symbol", None),
            arg_params=arg_params, aux_params=aux_params,
            optimizer_states=states, background=background,
            job_state=job_state)

    def _write_and_commit_guarded(self, files, entry):
        try:
            self._write_and_commit(files, entry)
        except Exception as exc:
            self.logger.error("background checkpoint save failed: %s", exc)
            with self._plock:
                self._bg_error = exc

    def _write_and_commit(self, files, entry):
        import time
        t0 = time.perf_counter()
        dirname = self.dirname
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        with self._lock:
            for name, data in sorted(files.items()):
                atomic_write(os.path.join(dirname, name), data)
            # the commit point: only a manifest entry makes the files
            # above part of the checkpoint history
            chaos.on_commit(self.manifest_path)
            man = self._read_manifest()
            entries = [e for e in man["checkpoints"]
                       if e["epoch"] != entry["epoch"]]
            entries.append(entry)
            entries.sort(key=lambda e: e["epoch"])
            dropped = []
            if self.keep_last > 0 and len(entries) > self.keep_last:
                dropped = entries[:-self.keep_last]
                entries = entries[-self.keep_last:]
            man["version"] = MANIFEST_VERSION
            man["checkpoints"] = entries
            atomic_write(self.manifest_path,
                         (json.dumps(man, indent=1, sort_keys=True)
                          + "\n").encode("utf-8"))
            self._delete_orphans(dropped, entries)
            # after the commit, refresh the legacy-named convenience
            # copy (outside the integrity guarantee — the reference's
            # loaders expect `<prefix>-symbol.json`)
            for name, data in files.items():
                if name.endswith("-symbol.json"):
                    atomic_write("%s-symbol.json" % self.prefix, data)
        elapsed = time.perf_counter() - t0
        total_bytes = sum(len(d) for d in files.values())
        _metrics.counter("checkpoint_saves_total",
                         "committed checkpoint saves").inc()
        _metrics.counter("checkpoint_bytes_total",
                         "bytes durably written by committed "
                         "checkpoint saves").inc(total_bytes)
        _metrics.histogram("checkpoint_save_seconds",
                           "write+fsync+commit latency of one "
                           "checkpoint save").observe(elapsed)
        _obs_events.emit("checkpoint", action="commit",
                         epoch=entry["epoch"], prefix=self.prefix,
                         files=len(files), bytes=total_bytes,
                         seconds=round(elapsed, 4))

    def _delete_orphans(self, dropped, kept):
        still_referenced = set()
        for e in kept:
            still_referenced.update(e["files"])
        for e in dropped:
            for name in e["files"]:
                if name in still_referenced:
                    continue
                try:
                    os.unlink(os.path.join(self.dirname, name))
                except OSError:
                    pass

    def wait(self):
        """Join outstanding background saves; re-raise the first
        background failure."""
        with self._plock:
            pending, self._pending = self._pending, []
        for t in pending:
            t.join()
        self._raise_pending()

    def _raise_pending(self):
        with self._plock:
            exc, self._bg_error = self._bg_error, None
        if exc is not None:
            raise exc

    # -- restore -----------------------------------------------------------
    def _verify_entry(self, entry):
        """'' when intact, else a human-readable reason.  Hashes in
        1 MiB chunks — multi-GB params files must not be slurped into
        one allocation just to be verified."""
        for name, meta in entry["files"].items():
            path = os.path.join(self.dirname, name)
            digest = hashlib.sha256()
            size = 0
            try:
                with open(path, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        digest.update(chunk)
                        size += len(chunk)
            except OSError as exc:
                return "%s unreadable (%s)" % (name, exc)
            if size != meta["size"]:
                return "%s truncated (%d bytes, manifest says %d)" % (
                    name, size, meta["size"])
            if digest.hexdigest() != meta["sha256"]:
                return "%s checksum mismatch" % name
        return ""

    def verify(self, epoch):
        """True/False for a committed epoch; None when the manifest has
        no entry for it (legacy checkpoint without a manifest)."""
        for entry in self._read_manifest()["checkpoints"]:
            if entry["epoch"] == int(epoch):
                return not self._verify_entry(entry)
        return None

    def restore_latest(self):
        """Newest fully-intact checkpoint (every file present, sized,
        and checksum-verified) as a :class:`CheckpointRecord`; corrupt
        or torn entries are skipped with a warning.  None when nothing
        intact exists."""
        self.wait()
        entries = self._read_manifest()["checkpoints"]
        for entry in reversed(entries):
            reason = self._verify_entry(entry)
            if not reason:
                files = {name: os.path.join(self.dirname, name)
                         for name in entry["files"]}
                _obs_events.emit("checkpoint", action="restore",
                                 epoch=entry["epoch"],
                                 prefix=self.prefix)
                return CheckpointRecord(entry["epoch"], self.dirname,
                                        files)
            _obs_events.emit("checkpoint", action="skip_corrupt",
                             epoch=entry["epoch"], prefix=self.prefix,
                             reason=reason)
            self.logger.warning(
                "checkpoint epoch %d is corrupt (%s); falling back to "
                "the previous one", entry["epoch"], reason)
        return None
