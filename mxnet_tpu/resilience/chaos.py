"""Deterministic fault-injection harness (chaos engineering for the
run side — the TPU-era mirror of the reference's ps-lite dead-node
drills, which exercised server replication by killing processes).

Injection points are consulted by the production code itself
(checkpoint writer, fused train step, fit loop), so a chaos-enabled
test run drives the EXACT recovery paths a preempted TPU job takes —
no mocks of the code under test.  Everything is counter-based and
deterministic: no randomness, no sleeps.

Activation: programmatic :func:`configure` wins; otherwise the
``MXNET_CHAOS`` env knob supplies a spec string such as
``"fail_file_writes=2,nan_grads_at_step=3,preempt_at_batch=5"``
(bare ``on``/``1`` enables the harness with no injections armed).

Spec keys (all integers):

``fail_file_writes=N``
    The next N atomic file writes raise ``OSError`` before touching
    disk (transient-storage failure; exercises retry/backoff).
``kill_mid_save=N``
    The next N atomic writes crash AFTER the tmp file is written but
    BEFORE ``os.replace`` — a preemption mid-checkpoint.  Raises
    :class:`SimulatedCrash` (a ``BaseException``, so ordinary
    ``except Exception`` recovery code cannot accidentally survive
    it, same as a real SIGKILL; the tmp file is left behind exactly
    like a real kill would).
``kill_before_commit=N``
    Crash after a checkpoint's data files are durably written but
    before the manifest commit — the classic torn-metadata window.
``corrupt_checkpoint_bytes=N``
    The next N non-manifest checkpoint files get their leading bytes
    flipped on disk AFTER the atomic replace (bit rot / torn storage
    under a manifest that still records the intended checksum).
``nan_grads_at_step=K``
    The K-th ``forward_backward_update`` call (0-based, per module)
    has its input batch poisoned with NaN so loss and every gradient
    go non-finite — exercises the in-graph guard.
``preempt_at_batch=N``
    ``preemption_requested()`` turns true once the fit loop has
    ticked N batch boundaries.
``kill_at_step=K``
    The process hard-exits (``os._exit(137)``, same code as SIGKILL)
    at the START of global training step K (0-based, the module's
    resumable ``step_seq``).  NOTE: a job killed at K resumes AT K —
    the same static spec re-kills every incarnation, so supervised
    drills must arm a different spec per attempt (the supervisor's
    ``env_for_attempt`` hook exists for exactly this; see
    ci/crash_anywhere_drill.py).
``hang_at_step=K``
    The training step wedges in an interruptible sleep loop at global
    step K — a stand-in for a wedged collective or deadlocked
    dataloader.  The heartbeat stops ticking and the supervisor's
    watchdog must detect it (``MXNET_WATCHDOG_TIMEOUT``), dump a
    flight record, and kill/restart.

Network-layer keys (``net_*``) ride the same spec and are consulted
by the distributed KVStore's socket choke points — see
:mod:`~mxnet_tpu.resilience.netchaos` for the catalogue
(drop / delay / duplicate / torn-frame / partition / server-kill).
"""

from __future__ import annotations

import logging
import os
import threading

from .. import sanitizer as _san

__all__ = ["SimulatedCrash", "configure", "reset", "active", "enabled",
           "consume", "fired", "note_injection", "on_file_write",
           "on_pre_replace", "on_commit", "on_post_replace",
           "maybe_poison_batch", "tick", "counter",
           "preemption_requested", "on_train_step"]

log = logging.getLogger(__name__)


class SimulatedCrash(BaseException):
    """An injected hard kill.  Subclasses ``BaseException`` on purpose:
    recovery code written as ``except Exception`` must not be able to
    'survive' a crash the way it never could survive SIGKILL."""


_lock = _san.lock(label="chaos._lock")
_spec = None        # programmatic spec (dict) — None = env-driven
_used = {}          # injection key -> how many times it already fired
_ticks = {}         # named event counters (fit batch boundaries, ...)


def _parse_spec(raw):
    spec = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if not val:
            continue
        try:
            spec[key] = int(val)
        except ValueError:
            raise ValueError(
                "MXNET_CHAOS: %r is not an integer in %r" % (val, raw))
    return spec


def active():
    """The active injection spec (programmatic beats env); {} when the
    harness is idle."""
    with _lock:
        if _spec is not None:
            return dict(_spec)
    from ..config import get_env
    raw = get_env("MXNET_CHAOS").strip()
    if not raw or raw.lower() in ("0", "off", "false"):
        return {}
    if raw.lower() in ("1", "on", "true"):
        return {}
    return _parse_spec(raw)


def enabled():
    """True when chaos is switched on at all (even with nothing armed)."""
    with _lock:
        if _spec is not None:
            return True
    from ..config import get_env
    raw = get_env("MXNET_CHAOS").strip()
    return bool(raw) and raw.lower() not in ("0", "off", "false")


def configure(**spec):
    """Arm injections programmatically (resets fire/tick counters)."""
    global _spec
    with _lock:
        _spec = {k: int(v) for k, v in spec.items() if v is not None}
        _used.clear()
        _ticks.clear()


def reset():
    """Disarm everything and fall back to the env-driven spec."""
    global _spec
    with _lock:
        _spec = None
        _used.clear()
        _ticks.clear()


def _consume(key):
    """True (and advance the fire counter) while fires remain for *key*."""
    budget = active().get(key, 0)
    with _lock:
        fired = _used.get(key, 0)
        if fired < budget:
            _used[key] = fired + 1
            hit = fired + 1
        else:
            return False
    # outside the lock: the event log + counter are observability, the
    # fire accounting above is correctness
    from ..observability import events as _obs_events
    from ..observability import metrics as _metrics
    _metrics.counter("chaos_injections_total",
                     "chaos faults actually fired").inc()
    _obs_events.emit("chaos", injection=key, fire=hit, budget=budget)
    return True


# public name: injection points outside this module (netchaos, tests)
# consume budgets through the same accounting
consume = _consume


def fired(key):
    """How many times injection *key* has fired."""
    with _lock:
        return _used.get(key, 0)


def tick(name):
    """Advance (and return) a named event counter."""
    with _lock:
        _ticks[name] = _ticks.get(name, 0) + 1
        return _ticks[name]


def counter(name):
    with _lock:
        return _ticks.get(name, 0)


# -- injection points consulted by production code --------------------------

def on_file_write(path):
    """Atomic-writer entry: transient write failure."""
    if _consume("fail_file_writes"):
        log.warning("chaos: injected write failure for %s", path)
        raise OSError("chaos: injected transient write failure (%s)" % path)


def on_pre_replace(path):
    """Between tmp-file fsync and ``os.replace``: preemption mid-save."""
    if _consume("kill_mid_save"):
        log.warning("chaos: simulated crash before os.replace of %s", path)
        raise SimulatedCrash("killed mid-save before replacing %s" % path)


def on_commit(path):
    """Between checkpoint data files and the manifest commit."""
    if _consume("kill_before_commit"):
        log.warning("chaos: simulated crash before manifest commit %s",
                    path)
        raise SimulatedCrash("killed before manifest commit of %s" % path)


def on_post_replace(path):
    """After the atomic replace: flip bytes on disk (bit rot / torn
    storage) — manifest checksums must catch this at restore time."""
    if path.endswith(".manifest.json"):
        return
    if _consume("corrupt_checkpoint_bytes"):
        log.warning("chaos: corrupting on-disk bytes of %s", path)
        with open(path, "r+b") as f:
            head = f.read(16)
            f.seek(0)
            f.write(bytes(b ^ 0xFF for b in head))
            f.flush()


def maybe_poison_batch(batch, step):
    """``nan_grads_at_step=K``: return a NaN-poisoned copy of *batch*
    when *step* == K (the caller's own batch object is not mutated)."""
    k = active().get("nan_grads_at_step")
    if k is None or step != k:
        return batch
    import copy
    from ..observability import events as _obs_events
    from ..observability import metrics as _metrics
    _metrics.counter("chaos_injections_total",
                     "chaos faults actually fired").inc()
    _obs_events.emit("chaos", injection="nan_grads_at_step", step=step)
    log.warning("chaos: poisoning batch at step %d with NaN", step)
    poisoned = copy.copy(batch)
    poisoned.data = [d * float("nan") for d in batch.data]
    return poisoned


def note_injection(key, **fields):
    """Account an injection that fired through index comparison rather
    than the budgeted :func:`consume` path (``*_at_step`` keys, the
    servechaos tick-indexed keys): bumps the fired table, the
    ``chaos_injections_total`` counter and the chaos event trail."""
    with _lock:
        _used[key] = _used.get(key, 0) + 1
    from ..observability import events as _obs_events
    from ..observability import metrics as _metrics
    _metrics.counter("chaos_injections_total",
                     "chaos faults actually fired").inc()
    _obs_events.emit("chaos", injection=key, **fields)


def _note_step_injection(key, step):
    note_injection(key, step=step)


# patchable seam (tests assert the kill without dying; mirrors
# netchaos._exit)
_exit = os._exit
_hang_sleep = None      # tests swap in a raising sleep to bound the hang


def on_train_step(step):
    """``kill_at_step=K`` / ``hang_at_step=K``: consulted by every
    training entry point at the START of global (resumable, 0-based)
    step *step*.  A kill is a hard ``os._exit(137)`` — no Python
    unwinding, exactly like SIGKILL; a hang is an interruptible sleep
    loop the watchdog must catch.  The resumable step index means a
    spec can target steps a previous incarnation never reached, but a
    job killed at K resumes AT K — re-arm a different spec per
    incarnation (supervisor ``env_for_attempt``) or the same fault
    re-fires."""
    spec = active()
    k = spec.get("kill_at_step")
    if k is not None and step == k:
        _note_step_injection("kill_at_step", step)
        log.warning("chaos: hard-killing the process at train step %d",
                    step)
        _exit(137)
    h = spec.get("hang_at_step")
    if h is not None and step == h:
        _note_step_injection("hang_at_step", step)
        log.warning("chaos: hanging the training loop at step %d "
                    "(watchdog bait)", step)
        import time as _time
        sleep = _hang_sleep or _time.sleep
        while True:
            sleep(0.25)


def preemption_requested():
    """True once the fit loop ticked ``preempt_at_batch`` boundaries."""
    n = active().get("preempt_at_batch")
    if n is None:
        return False
    return counter("fit_batch") >= n
