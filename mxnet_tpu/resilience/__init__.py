"""Resilience subsystem: crash-safe checkpoints, retry/backoff, fault
injection, and divergence/preemption signalling.

The reference framework's run-side robustness lived in ps-lite (server
replication, resenders) and a C++ engine that was never half-killed
mid-write; this rebuild replaces that with host-side machinery the
ROADMAP's production stance needs on preemptible hardware:

* :mod:`~mxnet_tpu.resilience.checkpoint` — atomic writes + checksum
  manifest, keep-last-K rotation, background saves, and
  ``restore_latest()`` fallback past torn/corrupt checkpoints;
* :mod:`~mxnet_tpu.resilience.retry` — jittered-exponential-backoff
  with a deadline and an injectable clock;
* :mod:`~mxnet_tpu.resilience.chaos` — deterministic fault injection
  driving the same code paths in CI;
* :mod:`~mxnet_tpu.resilience.netchaos` — the network-layer injection
  points (drop / delay / duplicate / torn-frame / partition /
  server-kill) the distributed KVStore's socket choke points consult;
* :mod:`~mxnet_tpu.resilience.servechaos` — the serving-path injection
  points (dispatch raise / hang / slow, warm-compile reject) the
  serve dispatcher and predictor consult;
* :mod:`~mxnet_tpu.resilience.jobstate` — :class:`TrainJobState`, the
  mid-epoch-resume snapshot (epoch/batch cursor, RNG + step counters,
  metric + data-pipeline state) checkpoints carry next to params;
* :mod:`~mxnet_tpu.resilience.supervisor` — heartbeat + hang
  watchdog + flight records + bounded auto-restart: run the training
  loop as a supervised child and a kill or hang at ANY step resumes
  from the latest checkpoint (see docs/resilience.md);
* :mod:`~mxnet_tpu.resilience.elastic` — the operator control plane
  for elastic dist_sync training: :func:`~elastic.operator_resize`
  rescales a RUNNING job N→M without a restart (the kvstore's live
  membership layer applies it at a sync-round boundary);
* the in-graph non-finite guard lives device-side (see
  ``optimizer/tree_opt.py`` and ``Executor.init_fused_step``); this
  package supplies its host-side :class:`DivergenceError`;
* a process-wide preemption flag the ``fit`` loop polls at batch
  boundaries (wire it to SIGTERM with
  :func:`install_preemption_handler`).

Import-light by design: nothing here imports jax, so the chaos/retry
machinery is usable from dataloader worker processes too.
"""

from __future__ import annotations

import threading

from ..base import MXNetError
from . import chaos  # noqa: F401
from . import elastic  # noqa: F401
from . import netchaos  # noqa: F401
from . import servechaos  # noqa: F401
from . import supervisor  # noqa: F401
from .checkpoint import (CheckpointManager, CheckpointRecord,  # noqa: F401
                         atomic_write)
from .jobstate import TrainJobState  # noqa: F401
from .retry import retry, retry_call  # noqa: F401

__all__ = ["CheckpointManager", "CheckpointRecord", "atomic_write",
           "retry", "retry_call", "chaos", "elastic", "netchaos",
           "servechaos", "supervisor",
           "TrainJobState", "DivergenceError", "StateMismatchError",
           "request_preemption", "clear_preemption",
           "preemption_requested", "install_preemption_handler"]


class DivergenceError(MXNetError):
    """Raised when the non-finite guard saw N consecutive bad steps
    and the configured divergence action is 'raise' (or a rollback
    found no intact checkpoint)."""


class StateMismatchError(MXNetError):
    """Raised when a restored optimizer-state blob was written by a
    different optimizer class or with different baked hyper-params
    than the one about to consume it — silently applying the stale
    state after a resume is exactly the bug this turns loud.  Set
    ``MXNET_OPTSTATE_MISMATCH=reinit`` to warn and re-initialize
    instead."""


_preempt_flag = threading.Event()


def request_preemption():
    """Ask the training loop to checkpoint and exit at the next batch
    boundary (safe to call from a signal handler or another thread)."""
    _preempt_flag.set()


def clear_preemption():
    _preempt_flag.clear()


def preemption_requested(tick=False):
    """True when a preemption was requested — programmatically, or by
    the chaos harness's ``preempt_at_batch`` point.  With ``tick=True``
    the chaos batch counter advances too; the fit loop calls this form
    exactly once per batch boundary."""
    if tick:
        chaos.tick("fit_batch")
    return _preempt_flag.is_set() or chaos.preemption_requested()


def install_preemption_handler(signals=None):
    """Install signal handlers that set the preemption flag (default:
    SIGTERM — what most preemptible-VM managers send).  Returns the
    mapping of previous handlers so callers can restore them."""
    import signal as _signal
    if signals is None:
        signals = (_signal.SIGTERM,)
    previous = {}
    for sig in signals:
        previous[sig] = _signal.signal(
            sig, lambda signum, frame: request_preemption())
    return previous
