"""Deterministic fault injection for the serving request path.

:mod:`~mxnet_tpu.resilience.chaos` covers process/filesystem faults
and :mod:`~mxnet_tpu.resilience.netchaos` the distributed transport;
this module covers the serve choke points.  The injection points are
consulted by the PRODUCTION serving code — the
:class:`~mxnet_tpu.serve.batcher.DynamicBatcher` dispatcher right
before it runs a coalesced batch, and
:meth:`~mxnet_tpu.serve.predictor.CompiledPredictor.ensure_program`
before an AOT build — so a chaos-enabled drill drives the exact
supervision / shedding / drain code a real serving outage exercises.

Everything rides the same counter-based ``MXNET_CHAOS`` spec (or
programmatic ``chaos.configure``).  Spec keys (all integers):

``dispatch_raise_at=K`` (+ optional ``dispatch_raise_for=N``)
    Raise ``RuntimeError`` on the K-th coalesced dispatch (1-based
    tick, process-wide until ``chaos.configure``/``reset``), and —
    with ``dispatch_raise_for=N`` — on the following N-1 dispatches
    too.  The raise happens OUTSIDE the batcher's per-batch error
    isolation, so it escapes the dispatcher loop: supervision must
    fail exactly that batch's futures and restart the thread
    (bounded by ``MXNET_SERVE_DISPATCHER_RESTARTS``).
``dispatch_hang_at=K``
    The K-th dispatch wedges in an interruptible sleep loop — a
    stand-in for a wedged device or deadlocked runtime.  The
    dispatcher's liveness tick goes stale (the health surface must
    flag it); :func:`release_hangs` lets the drill un-wedge it.
``slow_dispatch_ms=X``
    Every dispatch sleeps X milliseconds first while armed — backs
    the queue up so overload shedding and deadline expiry trigger
    deterministically without real load.
``reject_warm_at=K``
    The K-th AOT program build (warm or on-demand) raises a typed
    :class:`~mxnet_tpu.serve.buckets.ServeError` — a model whose
    load/warm fails must never half-register.

Fleet-scope keys (PR: multi-replica serving).  The replica-side
points are consulted by :class:`~mxnet_tpu.serve.replica.ReplicaServer`
connection handlers (arm them through a replica process's own
``MXNET_CHAOS`` env); the router-side point by
:meth:`~mxnet_tpu.serve.router.Router` right before a frame goes out
on a replica socket (arm via ``chaos.configure`` in the router's
process):

``replica_kill_at=K``
    The replica process hard-exits (``os._exit(137)``, patchable
    ``_exit`` seam) on receiving its K-th PREDICT request — BEFORE
    dispatch, so the router sees the connection die mid-request and
    must fail the request over to another replica.
``replica_kill_decode_at=K``
    Same hard-exit, but counting DECODE_* requests (OPEN/NEXT): the
    replica dies mid-stream, so the router must re-open every live
    decode session on a healthy replica from its journal and resume
    bit-equal.
``decode_tick_raise_at=K`` (+ optional ``decode_tick_raise_for=N``)
    Raise ``RuntimeError`` out of the K-th decode-engine tick (and
    the following N-1 with ``decode_tick_raise_for``) — the crash
    escapes the DecodeBatcher loop mid-donation, so the suspect pool
    must be quarantined and rebuilt (bounded by
    ``MXNET_SERVE_DECODE_REBUILDS``) with journaled sessions
    re-admitted via re-prefill.
``slow_replica_ms=X`` (+ optional ``slow_replica_for=N``)
    Every PREDICT (or the first N with ``slow_replica_for``) sleeps
    X milliseconds before dispatch — the straggling-replica bait for
    request hedging and breaker drills.
``fleet_partition_at=K`` (+ optional ``fleet_partition_for=N``,
``fleet_partition_port=P``)
    The K-th (through K+N-1-th) router->replica send raises
    ``ConnectionError`` without touching the wire — a router<->replica
    network partition; the router must fail over, the breaker must
    open, and the replica must rejoin once probes get through again.
    With ``fleet_partition_port=P`` only sends to the replica on port
    P count (and are cut), so a drill partitions ONE replica
    deterministically while probes to the others flow.

See ci/serve_chaos_drill.py and ci/fleet_chaos_drill.py for the
drills that exercise every class.
"""

from __future__ import annotations

import logging
import os
import time

from . import chaos
from .. import sanitizer as _san

__all__ = ["on_dispatch", "on_warm", "on_replica_request",
           "on_replica_decode", "on_decode_tick", "on_router_send",
           "release_hangs", "reset_hangs"]

log = logging.getLogger(__name__)

# drills wedge a dispatcher with dispatch_hang_at, observe the stale
# liveness tick, then release it — a plain event, settable from any
# thread (cleared again by reset_hangs for the next scenario)
_hang_release = _san.event()

# patchable seam so unit tests can bound the hang without the event
_hang_sleep = None


def release_hangs():
    """Un-wedge every dispatcher currently wedged by
    ``dispatch_hang_at`` (and any future hang until
    :func:`reset_hangs`)."""
    _hang_release.set()


def reset_hangs():
    """Re-arm the hang gate (the next ``dispatch_hang_at`` injection
    wedges again)."""
    _hang_release.clear()


def on_dispatch(name):
    """Serve dispatch choke point, consulted by the batcher's
    dispatcher thread for every coalesced batch BEFORE padding/
    dispatch and outside its per-batch error isolation.  May sleep
    (``slow_dispatch_ms``), wedge (``dispatch_hang_at``) or raise
    (``dispatch_raise_at``)."""
    if not chaos.enabled():
        return
    spec = chaos.active()
    slow = spec.get("slow_dispatch_ms")
    if slow:
        time.sleep(slow / 1000.0)
    raise_at = spec.get("dispatch_raise_at")
    hang_at = spec.get("dispatch_hang_at")
    if raise_at is None and hang_at is None:
        return
    n = chaos.tick("serve_dispatch")
    if raise_at is not None and \
            raise_at <= n < raise_at + spec.get("dispatch_raise_for", 1):
        chaos.note_injection("dispatch_raise_at", at=n, batcher=name)
        log.warning("servechaos: raising on dispatch %d of batcher %r",
                    n, name)
        raise RuntimeError(
            "servechaos: injected dispatch failure (batch %d, "
            "batcher %r)" % (n, name))
    if hang_at is not None and n == hang_at:
        chaos.note_injection("dispatch_hang_at", at=n, batcher=name)
        log.warning("servechaos: hanging dispatcher of batcher %r at "
                    "dispatch %d (health-surface bait)", name, n)
        sleep = _hang_sleep or (lambda s: _hang_release.wait(s))
        while not _hang_release.is_set():
            sleep(0.02)


# patchable seam so unit tests can assert the kill without dying
# (mirrors chaos._exit / netchaos._exit)
_exit = os._exit


def on_replica_request(replica):
    """Replica-side fleet choke point, consulted by the replica's
    connection handler for every PREDICT request BEFORE it reaches
    the registry.  ``replica_kill_at=K`` hard-exits the process on
    the K-th request (the router must fail over mid-request);
    ``slow_replica_ms`` makes this replica a straggler (hedging /
    breaker bait)."""
    if not chaos.enabled():
        return
    spec = chaos.active()
    kill_at = spec.get("replica_kill_at")
    slow = spec.get("slow_replica_ms")
    if kill_at is None and slow is None:
        return
    n = chaos.tick("replica_predict")
    if slow and n <= spec.get("slow_replica_for", 1 << 62):
        chaos.note_injection("slow_replica_ms", at=n, replica=replica)
        time.sleep(slow / 1000.0)
    if kill_at is not None and n == kill_at:
        chaos.note_injection("replica_kill_at", at=n, replica=replica)
        log.warning("servechaos: hard-killing replica %r at predict "
                    "%d", replica, n)
        _exit(137)


def on_replica_decode(replica):
    """Replica-side decode choke point, consulted by the replica's
    connection handler for every DECODE_OPEN / DECODE_NEXT request
    BEFORE it reaches the decode batcher.  ``replica_kill_decode_at=K``
    hard-exits the process on the K-th decode request — the router
    must re-open this replica's live sessions elsewhere from their
    journals and resume them bit-equal."""
    if not chaos.enabled():
        return
    kill_at = chaos.active().get("replica_kill_decode_at")
    if kill_at is None:
        return
    n = chaos.tick("replica_decode")
    if n == kill_at:
        chaos.note_injection("replica_kill_decode_at", at=n,
                             replica=replica)
        log.warning("servechaos: hard-killing replica %r at decode "
                    "request %d", replica, n)
        _exit(137)


def on_decode_tick(name):
    """Decode tick choke point, consulted by
    :meth:`~mxnet_tpu.serve.decode.DecodeEngine.tick` before the
    coalesced tick dispatch.  ``decode_tick_raise_at=K`` (+
    ``decode_tick_raise_for=N``) raises ``RuntimeError`` so the crash
    escapes the DecodeBatcher loop mid-donation — the
    quarantine-and-rebuild path (fresh pool, warm programs, journaled
    re-admission) must run."""
    if not chaos.enabled():
        return
    spec = chaos.active()
    raise_at = spec.get("decode_tick_raise_at")
    if raise_at is None:
        return
    n = chaos.tick("decode_tick")
    if raise_at <= n < raise_at + spec.get("decode_tick_raise_for", 1):
        chaos.note_injection("decode_tick_raise_at", at=n, engine=name)
        log.warning("servechaos: raising on decode tick %d of engine "
                    "%r", n, name)
        raise RuntimeError(
            "servechaos: injected decode tick failure (tick %d, "
            "engine %r)" % (n, name))


def on_router_send(replica, port=None):
    """Router-side fleet choke point, consulted right before a frame
    goes out on a replica socket.  ``fleet_partition_at=K`` (+
    ``fleet_partition_for=N``) simulates a router<->replica network
    partition: the send raises ``ConnectionError`` without touching
    the wire, so the router's failover/breaker path runs exactly as
    it would on a real partition.  ``fleet_partition_port=P``
    restricts the cut (and its tick counter) to the replica on port
    P."""
    if not chaos.enabled():
        return
    spec = chaos.active()
    at = spec.get("fleet_partition_at")
    if at is None:
        return
    pfilter = spec.get("fleet_partition_port")
    if pfilter and port != pfilter:
        return
    n = chaos.tick("fleet_send")
    if at <= n < at + spec.get("fleet_partition_for", 1):
        chaos.note_injection("fleet_partition_at", at=n,
                             replica=replica)
        log.warning("servechaos: partitioning router<->replica %r at "
                    "send %d", replica, n)
        raise ConnectionError(
            "servechaos: injected router<->replica partition "
            "(send %d, replica %r)" % (n, replica))


def on_warm(model):
    """AOT-build choke point (``CompiledPredictor.ensure_program``):
    ``reject_warm_at=K`` fails the K-th program build with a typed
    ServeError."""
    if not chaos.enabled():
        return
    k = chaos.active().get("reject_warm_at")
    if not k:
        return
    n = chaos.tick("serve_warm")
    if n == k:
        chaos.note_injection("reject_warm_at", at=n, model=model)
        log.warning("servechaos: failing program build %d of model %r",
                    n, model)
        from ..serve.buckets import ServeError
        raise ServeError(
            "servechaos: injected warm-compile failure (build %d, "
            "model %r)" % (n, model))
