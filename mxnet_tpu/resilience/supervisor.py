"""Job supervisor: heartbeat, hang watchdog, flight records, and
bounded auto-restart — the process-level layer of the resilience
subsystem.

PR 3 made *checkpoints* crash-safe and the fit loop preemption-aware;
this module makes the JOB survive: the training loop runs as a
supervised child that ticks a heartbeat file once per batch, and the
parent's watchdog distinguishes

* **dead** — ``waitpid`` reaped the child (preemption, OOM-kill,
  segfault, a chaos ``kill_at_step``): restart from the latest
  checkpoint with bounded, jitter-backed-off attempts;
* **hung** — the child is alive but the heartbeat has not advanced
  within ``MXNET_WATCHDOG_TIMEOUT`` (a wedged collective, a
  deadlocked dataloader, a chaos ``hang_at_step``): dump a **flight
  record** first (all-thread stacks via the child's ``faulthandler``
  SIGUSR1 hook, a metrics ``snapshot()`` via its SIGUSR2 hook, the
  tail of ``events.jsonl`` and the last compile-blame event), then
  kill and restart the same way.

Everything timing-related runs on ``time.monotonic`` — a watchdog
that dies to an NTP step is worse than no watchdog (graftlint JG012
exists because of exactly this hazard).

Child-side contract: call :func:`heartbeat` once per batch
(``fit()`` and ``ParallelTrainer.fit()`` do this automatically).  The
first tick lazily opens the file named by ``MXNET_HEARTBEAT_FILE``
and arms the SIGUSR1/SIGUSR2 flight hooks when
``MXNET_FLIGHT_STACKS``/``MXNET_FLIGHT_SNAPSHOT`` name their dump
paths — with none of the env knobs set every call is one dict lookup
and a return.

Import-light like the rest of the package: no jax anywhere here.
"""

from __future__ import annotations

import json
import logging
import os
import random
import signal
import subprocess
import sys
import time

from .retry import backoff_delays

__all__ = ["heartbeat", "reset_heartbeat", "read_heartbeat",
           "Supervisor", "SupervisorResult", "run_supervised"]

log = logging.getLogger(__name__)

_TICK_WIDTH = 20        # fixed-width counter: a reader never sees a
#                         torn number (single small pwrite at offset 0)

# child-side heartbeat state: path -> (fd, count)
_hb_state = {}


def _install_flight_hooks():
    """Arm the child-side flight-record hooks (idempotent).

    SIGUSR1 -> ``faulthandler`` all-thread stack dump (C-level: works
    even when every Python thread is wedged); SIGUSR2 -> best-effort
    Python-level metrics snapshot (works for sleep-style hangs, where
    the interpreter still runs signal handlers)."""
    stacks = os.environ.get("MXNET_FLIGHT_STACKS")
    if stacks:
        try:
            import faulthandler
            f = open(stacks, "w")
            faulthandler.register(signal.SIGUSR1, file=f,
                                  all_threads=True)
        except (OSError, ValueError, AttributeError) as exc:
            log.debug("flight stacks hook not installed: %s", exc)
    snap = os.environ.get("MXNET_FLIGHT_SNAPSHOT")
    if snap:
        def _dump_snapshot(signum, frame):
            try:
                from ..observability import metrics as _metrics
                payload = {"metrics": _metrics.snapshot(),
                           "pid": os.getpid()}
                with open(snap, "w", encoding="utf-8") as f:
                    json.dump(payload, f, default=repr)
            except Exception:   # signal context: never propagate
                pass
        try:
            signal.signal(signal.SIGUSR2, _dump_snapshot)
        except (ValueError, OSError) as exc:
            # not the main thread / platform without SIGUSR2
            log.debug("flight snapshot hook not installed: %s", exc)


def heartbeat():
    """Tick the supervised-job heartbeat (one per batch).  No-op
    unless ``MXNET_HEARTBEAT_FILE`` is set.  Returns the tick count
    (0 = unsupervised)."""
    path = os.environ.get("MXNET_HEARTBEAT_FILE")
    if not path:
        return 0
    state = _hb_state.get(path)
    if state is None:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT, 0o644)
        state = _hb_state[path] = [fd, 0]
        _install_flight_hooks()
    state[1] += 1
    os.pwrite(state[0], b"%0*d" % (_TICK_WIDTH, state[1]), 0)
    return state[1]


def reset_heartbeat():
    """Close cached heartbeat fds (tests that swap env paths)."""
    for fd, _ in _hb_state.values():
        try:
            os.close(fd)
        except OSError:
            pass
    _hb_state.clear()


def read_heartbeat(path):
    """Parent-side: the child's tick count, or None before the first
    tick."""
    try:
        with open(path, "rb") as f:
            raw = f.read(_TICK_WIDTH)
    except OSError:
        return None
    raw = raw.strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class SupervisorResult:
    """Outcome of one supervised job."""

    __slots__ = ("exit_code", "attempts", "deaths", "hangs",
                 "flight_records")

    def __init__(self, exit_code, attempts, deaths, hangs,
                 flight_records):
        self.exit_code = exit_code
        self.attempts = attempts
        self.deaths = deaths
        self.hangs = hangs
        self.flight_records = list(flight_records)

    @property
    def ok(self):
        return self.exit_code == 0

    def __repr__(self):
        return ("SupervisorResult(exit_code=%r, attempts=%d, deaths=%d, "
                "hangs=%d, flight_records=%d)"
                % (self.exit_code, self.attempts, self.deaths,
                   self.hangs, len(self.flight_records)))


class Supervisor:
    """Run *cmd* (an argv list) as a supervised, auto-restarted child.

    Parameters
    ----------
    cmd : list of str
        The child process argv (typically ``[sys.executable, script]``);
        the child must resume from its own latest checkpoint on start
        (``fit(resume_from=...)``) — the supervisor restarts, it does
        not re-plan.
    workdir : str
        Where the heartbeat file and flight records live.
    timeout : float
        Hang threshold in seconds (default ``MXNET_WATCHDOG_TIMEOUT``):
        a child that is alive but has not ticked for this long is
        declared hung.  Measured on the monotonic clock.
    max_restarts : int
        Restart budget (default ``MXNET_SUPERVISOR_RESTARTS``); the
        first attempt is free, so up to ``max_restarts + 1`` runs.
    env / env_for_attempt :
        Base environment overrides, plus an optional
        ``env_for_attempt(attempt) -> dict`` hook so drills can arm a
        different chaos spec per incarnation.
    sleep / rng :
        Injectable (tests run deterministic schedules with no real
        sleeping); backoff is the shared ``resilience.retry`` policy.
    """

    def __init__(self, cmd, workdir, timeout=None, max_restarts=None,
                 env=None, env_for_attempt=None, poll_interval=0.1,
                 grace=2.0, base_delay=0.1, max_delay=5.0, jitter=0.5,
                 sleep=time.sleep, rng=None, logger=None):
        from ..config import get_env
        self.cmd = list(cmd)
        # absolute: the child runs with cwd=workdir and resolves the
        # heartbeat/flight env paths against THAT — a relative workdir
        # would double up (workdir/workdir/heartbeat) and kill every
        # incarnation on its first tick
        self.workdir = os.path.abspath(workdir)
        self.timeout = float(timeout if timeout is not None
                             else get_env("MXNET_WATCHDOG_TIMEOUT"))
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else get_env("MXNET_SUPERVISOR_RESTARTS"))
        self.env = dict(env or {})
        self.env_for_attempt = env_for_attempt
        self.poll_interval = poll_interval
        self.grace = grace
        self._backoff = dict(base_delay=base_delay, max_delay=max_delay,
                             multiplier=2.0, jitter=jitter)
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self.logger = logger or log
        self.heartbeat_path = os.path.join(self.workdir, "heartbeat")
        os.makedirs(self.workdir, exist_ok=True)

    # -- elastic scale hook ------------------------------------------------
    def resize_workers(self, world, host=None, root_port=None,
                       num_servers=None, timeout=30.0):
        """Drive an elastic rescale of the supervised dist_sync job to
        *world* workers (either direction) without restarting it: the
        operator-commanded path of docs/resilience.md "Elastic
        training".  Endpoints default to the supervised child's
        ``DMLC_*`` environment (``self.env`` first, then this
        process's).  Growing additionally needs the new worker
        processes started; shrunk-away ranks exit cleanly on their
        own."""
        from .elastic import operator_resize
        env = dict(os.environ)
        env.update(self.env)
        reply = operator_resize(
            world,
            host=host or env.get("DMLC_PS_ROOT_URI"),
            root_port=root_port if root_port is not None
            else env.get("DMLC_PS_ROOT_PORT"),
            num_servers=num_servers if num_servers is not None
            else env.get("DMLC_NUM_SERVER"),
            timeout=timeout)
        self.logger.warning("supervisor: commanded elastic resize to "
                            "%d worker(s): %s", world, reply)
        return reply

    # -- child lifecycle ---------------------------------------------------
    def _child_env(self, attempt):
        env = dict(os.environ)
        env.update(self.env)
        if self.env_for_attempt is not None:
            env.update(self.env_for_attempt(attempt) or {})
        env["MXNET_HEARTBEAT_FILE"] = self.heartbeat_path
        env["MXNET_FLIGHT_STACKS"] = self._stacks_path(attempt)
        env["MXNET_FLIGHT_SNAPSHOT"] = self._snapshot_path(attempt)
        env["MXNET_SUPERVISOR_ATTEMPT"] = str(attempt)
        return env

    def _stacks_path(self, attempt):
        return os.path.join(self.workdir, "flight-%d-stacks.txt" % attempt)

    def _snapshot_path(self, attempt):
        return os.path.join(self.workdir, "flight-%d-snapshot.json"
                            % attempt)

    def _spawn(self, attempt):
        # a fresh heartbeat file per attempt: a stale tick count from
        # the previous incarnation must not look like progress
        try:
            os.unlink(self.heartbeat_path)
        except OSError:
            pass
        return subprocess.Popen(self.cmd, env=self._child_env(attempt),
                                cwd=self.workdir)

    # -- flight record -----------------------------------------------------
    def _events_tail(self, limit=50):
        """Last *limit* events of the job's events.jsonl (parsed), and
        the newest compile event among them (the blame trail for "it
        hung right after that recompile")."""
        from ..observability import events as _events
        path = self.env.get("MXNET_OBS_PATH") or _events.path()
        if not os.path.isabs(path):
            path = os.path.join(self.workdir, path)
        tail = _events.tail_records(path, max_bytes=1 << 18)[-limit:]
        last_compile = None
        for rec in tail:
            if rec.get("ev") == "compile":
                last_compile = rec
        return tail, last_compile

    def _dump_flight_record(self, attempt, proc, reason, last_tick):
        """Assemble the flight record BEFORE killing a hung child:
        poke its faulthandler (SIGUSR1) and snapshot (SIGUSR2) hooks,
        give them a moment, then write one JSON next to the dumps."""
        path = os.path.join(self.workdir, "flight-%d.json" % attempt)
        stacks = self._stacks_path(attempt)
        snapshot = self._snapshot_path(attempt)
        if proc.poll() is None:
            for sig in (signal.SIGUSR1, signal.SIGUSR2):
                try:
                    proc.send_signal(sig)
                except OSError:
                    break
            deadline = time.monotonic() + self.grace
            while time.monotonic() < deadline:
                if os.path.exists(stacks) and \
                        os.path.getsize(stacks) > 0:
                    break
                self._sleep(0.05)
        tail, last_compile = self._events_tail()
        record = {
            "reason": reason,
            "attempt": attempt,
            "pid": proc.pid,
            "cmd": self.cmd,
            "last_heartbeat_tick": last_tick,
            "watchdog_timeout_s": self.timeout,
            "stacks_path": stacks if os.path.exists(stacks) else None,
            "snapshot_path": (snapshot if os.path.exists(snapshot)
                              else None),
            "events_tail": tail,
            "last_compile": last_compile,
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1, default=repr)
        os.replace(tmp, path)
        self.logger.warning("supervisor: flight record written to %s "
                            "(%s)", path, reason)
        return path

    # -- events / counters -------------------------------------------------
    def _emit(self, category, **fields):
        from ..observability import events as _events
        from ..observability import metrics as _metrics
        # the child appends to the same events.jsonl: reopen so this
        # writer re-reads the last seq and the combined log stays
        # monotone across the restart boundary
        _events.reopen()
        _events.emit(category, **fields)
        return _metrics

    # -- main loop ---------------------------------------------------------
    def run(self):
        """Supervise until the child exits 0, or the restart budget is
        spent (returns the last exit code; 124 stands in for a
        hang-kill)."""
        deaths = hangs = 0
        flight_records = []
        delays = backoff_delays(self.max_restarts + 1,
                                rng=self._rng, **self._backoff)
        attempt = 0
        while True:
            self._emit("supervisor", action="start", attempt=attempt,
                       restarts_used=deaths + hangs,
                       budget=self.max_restarts)
            proc = self._spawn(attempt)
            rc, reason, last_tick = self._watch(proc, attempt)
            if rc == 0:
                m = self._emit("supervisor", action="exit", attempt=attempt,
                               exit_code=0, deaths=deaths, hangs=hangs)
                return SupervisorResult(0, attempt + 1, deaths, hangs,
                                        flight_records)
            if reason == "hang":
                hangs += 1
                flight_records.append(
                    self._dump_flight_record(attempt, proc, "hang",
                                             last_tick))
                self._kill(proc)
                rc = 124
                m = self._emit("watchdog", action="hang_killed",
                               attempt=attempt, last_tick=last_tick,
                               timeout_s=self.timeout)
                m.counter("watchdog_hangs_total",
                          "supervised children killed for a stalled "
                          "heartbeat").inc()
            else:
                deaths += 1
                m = self._emit("supervisor", action="child_died",
                               attempt=attempt, exit_code=rc,
                               last_tick=last_tick)
                m.counter("supervisor_child_deaths_total",
                          "supervised children reaped with a nonzero "
                          "exit").inc()
            if deaths + hangs > self.max_restarts:
                self._emit("supervisor", action="gave_up",
                           attempt=attempt, exit_code=rc,
                           deaths=deaths, hangs=hangs)
                self.logger.error(
                    "supervisor: restart budget exhausted (%d deaths + "
                    "%d hangs > %d restarts); giving up with exit code "
                    "%s", deaths, hangs, self.max_restarts, rc)
                return SupervisorResult(rc, attempt + 1, deaths, hangs,
                                        flight_records)
            delay = next(delays)
            m.counter("supervisor_restarts_total",
                      "supervised children restarted after a death or "
                      "hang-kill").inc()
            self._emit("supervisor", action="restart",
                       attempt=attempt + 1, backoff_s=round(delay, 3),
                       reason=reason, exit_code=rc)
            self.logger.warning(
                "supervisor: child %s (rc=%s, attempt %d); restarting "
                "from the latest checkpoint in %.2fs [%d/%d restarts]",
                reason, rc, attempt, delay, deaths + hangs,
                self.max_restarts)
            self._sleep(delay)
            attempt += 1

    def _watch(self, proc, attempt):
        """Poll until the child exits or hangs.  Returns
        ``(exit_code_or_None, reason, last_tick)`` where reason is
        'exit' or 'hang'."""
        last_tick = None
        last_change = time.monotonic()
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc, "exit", last_tick
            tick = read_heartbeat(self.heartbeat_path)
            now = time.monotonic()
            if tick != last_tick:
                last_tick = tick
                last_change = now
            elif tick is not None and now - last_change > self.timeout:
                return None, "hang", last_tick
            elif tick is None and now - last_change > 4 * self.timeout:
                # never ticked at all: likely wedged before the first
                # batch (import deadlock, stuck compile) — startup gets
                # 4x slack, then it is the same hang
                return None, "hang", last_tick
            self._sleep(self.poll_interval)

    def _kill(self, proc):
        if proc.poll() is not None:
            return
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.logger.error("supervisor: child %d survived SIGKILL "
                              "wait window", proc.pid)


def run_supervised(cmd, workdir, **kwargs):
    """One-call form: ``Supervisor(cmd, workdir, **kwargs).run()``."""
    return Supervisor(cmd, workdir, **kwargs).run()
