"""Elastic distributed training — the operator's control plane.

PR 7/8 built the primitives (straggler eviction, rejoin with
incarnation tokens, bit-exact mid-epoch resume); the kvstore's live
membership layer (``_kvstore_impl``: membership epochs, barrier-
boundary transitions, typed stale-contributor rejection) composes
them into elasticity.  This module is the thin operator-side entry
point: resize a RUNNING dist_sync job from any process — a
supervisor, a maintenance hook, a shell — without constructing a
full :class:`~mxnet_tpu.kvstore.KVStoreDist` (which would claim a
worker rank).

The protocol (docs/resilience.md "Elastic training"):

* every server versions its expected-contributor set with a
  **membership epoch**, carried on every heartbeat and sync reply;
* ``resize(M)`` records a pending world size on every server; it is
  APPLIED at the next barrier completion — the one instant a
  dist_sync job provably has no push in flight — so all workers see
  the transition in the same completed round's snapshot and re-shard
  at the same batch boundary;
* shrunk-away ranks find themselves outside the snapshot's member
  list and exit cleanly; any straggling push they still had on the
  wire is rejected with a typed
  :class:`~mxnet_tpu.kvstore.EvictedWorkerError`;
* grown slots fill as new workers heartbeat in: they are admitted at
  a barrier completion, learn their admission round via
  ``kv.wait_admission()``, and take over their shard from the
  job metadata the survivors publish (``kv.put_job_meta``).
"""

from __future__ import annotations

import logging
import os
import time

__all__ = ["operator_resize", "server_endpoints"]

log = logging.getLogger(__name__)


def server_endpoints(host=None, root_port=None, num_servers=None):
    """The (host, port) of every server of the launch, resolved from
    the standard ``DMLC_*`` env names when not given explicitly."""
    host = host or os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    root_port = int(root_port if root_port is not None
                    else os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    num_servers = int(num_servers if num_servers is not None
                      else os.environ.get("DMLC_NUM_SERVER", "1"))
    return [(host, root_port + s) for s in range(num_servers)]


def operator_resize(world, host=None, root_port=None, num_servers=None,
                    timeout=30.0):
    """Command a running dist_sync job to rescale to *world* workers
    (either direction) without a restart-from-checkpoint.

    Sends the ``resize`` command to every server of the group; each
    records the target and applies it at its next sync-round boundary.
    Returns server 0's acknowledgement (``{"world": current,
    "pending_world": target, "mep": epoch}``).  Growing past the
    launch size additionally needs the new worker processes started
    (with ``DMLC_WORKER_RANK`` = the new ranks); they announce
    themselves by heartbeating and are admitted at the next boundary.
    """
    from .._kvstore_impl import _connect_retry, _rpc_call, _MSG_CMD
    world = int(world)
    if world < 1:
        raise ValueError("resize target must be >= 1 worker, got %d"
                         % world)
    replies, failures = [], []
    for host_, port in server_endpoints(host, root_port, num_servers):
        # attempt EVERY server even after a failure: aborting midway
        # would leave the group with divergent resize targets and
        # nothing telling the operator which half recorded the command
        try:
            sock = _connect_retry(host_, port,
                                  time.monotonic() + timeout)
            try:
                sock.settimeout(timeout)
                replies.append(_rpc_call(
                    sock, _MSG_CMD,
                    {"head": "resize", "body": world})[0])
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
        except (ConnectionError, OSError) as exc:
            failures.append(("%s:%d" % (host_, port), exc))
    if failures:
        detail = ", ".join("%s (%s: %s)" % (ep, type(e).__name__, e)
                           for ep, e in failures)
        raise RuntimeError(
            "resize to %d acknowledged by %d/%d server(s); FAILED on "
            "%s — the group now has divergent resize targets: re-run "
            "operator_resize(%d) until every server acknowledges"
            % (world, len(replies), len(replies) + len(failures),
               detail, world))
    log.warning("operator resize to %d worker(s) acknowledged by %d "
                "server(s) (world was %s)", world, len(replies),
                replies[0].get("world") if replies else None)
    return replies[0] if replies else None
