"""TrainJobState — everything a killed training job needs beyond
params/optimizer state to resume *bit-exactly* mid-epoch.

A checkpoint of params + optimizer state alone resumes to the right
weights but the wrong JOB: the data iterator restarts at batch 0
(batches silently replayed), the PRNG key replays old dropout masks,
the metric forgets the epoch so far, and the guard counters reset.
``TrainJobState`` captures the rest — epoch, batch index, the
module's resumable RNG/step/guard fragment, the ``EvalMetric``
accumulator, and the data pipeline position (``DataIter.state_dict``
/ ``gluon.data.DataLoader.state_dict``) — and rides through
:class:`~mxnet_tpu.resilience.checkpoint.CheckpointManager` as one
more manifest-tracked (checksummed) file next to the ``.params`` /
``.states`` pair.

Serialization is JSON with an explicit key-encoding layer: every dict
is stored as a ``{"__jmap__": [[json(key), value], ...]}`` wrapper,
so int-keyed tables (optimizer per-index update counts, per-index
metric tallies) round-trip with their key TYPES intact — plain JSON
would silently stringify them and the resumed optimizer would start
fresh counts beside orphaned ``"0"``/``"1"`` entries.

Import-light on purpose (no jax): the jax-touching capture/restore
code lives in ``Module.job_state()`` / ``Executor.rng_state()``.
"""

from __future__ import annotations

import json

__all__ = ["TrainJobState", "encode_keyed", "decode_keyed"]

_WRAP = "__jmap__"


def encode_keyed(obj):
    """Recursively wrap dicts so non-string keys survive JSON."""
    if isinstance(obj, dict):
        return {_WRAP: [[json.dumps(k), encode_keyed(v)]
                        for k, v in obj.items()]}
    if isinstance(obj, (list, tuple)):
        return [encode_keyed(v) for v in obj]
    return obj


def decode_keyed(obj):
    if isinstance(obj, dict):
        if set(obj) == {_WRAP}:
            return {json.loads(k): decode_keyed(v) for k, v in obj[_WRAP]}
        # foreign plain dict (hand-written state): keys stay as-is
        return {k: decode_keyed(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_keyed(v) for v in obj]
    return obj


class TrainJobState:
    """One resumable snapshot of a training job at a batch boundary.

    ``epoch``/``nbatch`` locate the boundary: ``nbatch`` is the LAST
    COMPLETED batch of ``epoch`` (``-1`` = the state was captured at
    an epoch boundary and ``epoch`` is the next epoch to run).
    ``module`` is ``Module.job_state()``'s fragment (step_seq, guard
    counters, RNG key, optimizer update counts); ``metric`` is
    ``EvalMetric.state_dict()``; ``data`` is the iterator's
    ``state_dict()`` (None = position not capturable — resume replays
    the epoch's earlier batches into the void, which is loud in the
    drill's sequence log, not silent)."""

    VERSION = 1

    __slots__ = ("epoch", "nbatch", "module", "metric", "data", "extra")

    def __init__(self, epoch, nbatch, module=None, metric=None,
                 data=None, extra=None):
        self.epoch = int(epoch)
        self.nbatch = int(nbatch)
        self.module = module or {}
        self.metric = metric
        self.data = data
        self.extra = extra or {}

    def to_bytes(self):
        payload = {"version": self.VERSION,
                   "epoch": self.epoch,
                   "nbatch": self.nbatch,
                   "module": encode_keyed(self.module),
                   "metric": encode_keyed(self.metric),
                   "data": encode_keyed(self.data),
                   "extra": encode_keyed(self.extra)}
        return (json.dumps(payload, sort_keys=True) + "\n").encode("utf-8")

    @classmethod
    def from_bytes(cls, data):
        payload = json.loads(bytes(data).decode("utf-8"))
        version = payload.get("version")
        if version != cls.VERSION:
            raise ValueError(
                "TrainJobState version %r is not supported (this build "
                "reads version %d)" % (version, cls.VERSION))
        return cls(epoch=payload["epoch"], nbatch=payload["nbatch"],
                   module=decode_keyed(payload.get("module")) or {},
                   metric=decode_keyed(payload.get("metric")),
                   data=decode_keyed(payload.get("data")),
                   extra=decode_keyed(payload.get("extra")) or {})

    def __repr__(self):
        return ("TrainJobState(epoch=%d, nbatch=%d, module_keys=%s, "
                "metric=%s, data=%s)"
                % (self.epoch, self.nbatch, sorted(self.module),
                   "yes" if self.metric is not None else "no",
                   "yes" if self.data is not None else "no"))
