"""Quantization policy + the pipeline's typed error.

A :class:`QuantizePolicy` is the one knob object the whole pipeline
reads: which mode to lower to (weight+activation ``int8`` vs
``int8-weight-only``), which layers to leave fp32, and what the
load-time accuracy gate tolerates.  Everywhere a policy is accepted a
plain mode string works too (``QuantizePolicy.coerce``) — the registry,
the autotuner and bench all pass ``"int8"``-style strings around and
coerce at the boundary.
"""

from __future__ import annotations

__all__ = ["QuantizePolicy", "QuantizationError", "MODES"]

#: lowering modes, in increasing aggressiveness.  "off" is accepted by
#: coerce() (-> None) so a tuner Choice value can flow straight in.
MODES = ("int8-weight-only", "int8")


class QuantizationError(RuntimeError):
    """Typed failure of the quantization pipeline: a broken/mismatched
    calibration table, a model the lowering cannot honor, or a
    quantized model that failed the load-time accuracy gate.  Loads
    raise this instead of ever serving silently-wrong answers."""


class QuantizePolicy(object):
    """Controls lowering coverage and the accuracy gate.

    Parameters
    ----------
    mode : str
        ``"int8"`` — quantize activations AND weights; conv/fc run
        int8 x int8 -> int32 with fused requantize between adjacent
        quantized layers.  ``"int8-weight-only"`` — weights are stored
        and shipped int8 (dequantized in-graph); compute stays fp32.
    exclude : iterable of str
        Layer names the lowering must leave fp32 (per-layer opt-out).
    first_last_fp32 : bool
        Keep the first and last quantizable layer fp32 — the classic
        accuracy-preserving recipe for input/logit-adjacent layers.
    max_rel_err : float
        Accuracy gate: max |quantized - fp32| / max |fp32| allowed at
        every rung (relative worst-case error).
    min_top1_agreement : float or None
        Optional second gate: fraction of rows whose argmax matches
        fp32 (checked on the first 2-D output when set).
    gate_batches : int
        Synthetic gate batches per rung when the caller supplies no
        calibration batches to gate on.
    """

    def __init__(self, mode="int8", exclude=(), first_last_fp32=False,
                 max_rel_err=0.1, min_top1_agreement=None,
                 gate_batches=2):
        if mode not in MODES:
            raise QuantizationError(
                "unknown quantization mode %r (have %s)"
                % (mode, list(MODES)))
        self.mode = mode
        self.exclude = tuple(exclude)
        self.first_last_fp32 = bool(first_last_fp32)
        self.max_rel_err = float(max_rel_err)
        self.min_top1_agreement = (None if min_top1_agreement is None
                                   else float(min_top1_agreement))
        self.gate_batches = int(gate_batches)

    @property
    def needs_calib(self):
        """Weight+activation lowering needs calibrated activation
        ranges; weight-only quantizes offline from the weights."""
        return self.mode == "int8"

    @classmethod
    def coerce(cls, value):
        """Policy | mode string | dict -> QuantizePolicy (or None for
        off).  The single entry point every API boundary funnels
        through."""
        if value is None or value == "off":
            return None
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls(mode=value)
        if isinstance(value, dict):
            return cls(**value)
        raise QuantizationError(
            "cannot coerce %r into a QuantizePolicy" % (value,))

    def to_dict(self):
        return {"mode": self.mode, "exclude": list(self.exclude),
                "first_last_fp32": self.first_last_fp32,
                "max_rel_err": self.max_rel_err,
                "min_top1_agreement": self.min_top1_agreement,
                "gate_batches": self.gate_batches}

    def __repr__(self):
        return "QuantizePolicy(%s)" % ", ".join(
            "%s=%r" % kv for kv in sorted(self.to_dict().items()))
