"""Quantization lowering: fp32 Symbol -> int8 Symbol + param tree.

The Glow-style recipe (PAPERS.md), mapped onto this graph: walk the
fp32 graph in topological order keeping TWO representations per
tensor —

* the **fp32 entry** (always constructible; materialized lazily via
  ``_contrib_dequantize`` when a non-quantized consumer needs it), and
* the **quantized entry** (int8 value + symmetric range), present only
  along quantized chains.

A quantizable layer (Convolution / FullyConnected, policy permitting,
calibrated input range available) consumes the quantized entry when
its producer has one — so adjacent quantized layers are **fused
through a single int32->int8 requantize** against the calibrated
inter-layer range, with no dequantize/quantize round trip — and falls
back to inserting ``_contrib_quantize`` on the fp32 entry otherwise.
ReLU / Pooling / Flatten between quantized layers stay in the int8
domain (``_contrib_quantized_act`` / ``_contrib_quantized_pooling`` /
``_contrib_quantized_flatten``).  Every other op consumes fp32 —
the unsupported-op fallback is by construction, not by special case.

Weights are quantized OFFLINE (symmetric int8) into the returned
param tree; biases are requantized to int32 at the accumulator scale
``s_data * s_weight`` and added before the requantize, so the whole
conv/fc(+bias) block runs in integers.  ``int8-weight-only`` mode
keeps compute fp32 and only ships int8 weights (dequantized
in-graph): the memory-bound win without the activation-accuracy risk.
"""

from __future__ import annotations

import numpy as _np

from .calibrate import CalibTable, tensor_name
from .policy import QuantizePolicy, QuantizationError
from .. import ndarray as nd
from .. import symbol as S
from ..observability import events as _obs_events
from ..symbol.symbol import Node, Symbol

__all__ = ["quantize_model", "hlo_has_int8_compute",
           "hlo_has_int8_tensors"]

_QUANTIZABLE = ("Convolution", "FullyConnected")
_QCONV_PARAMS = ("kernel", "stride", "pad", "dilate", "num_filter",
                 "num_group")
_QFC_PARAMS = ("num_hidden", "flatten")
_INT32_MAX = 2 ** 31 - 1


def _np_of(v):
    asnumpy = getattr(v, "asnumpy", None)
    return asnumpy() if asnumpy is not None else _np.asarray(v)


def _scalar(x):
    return nd.array(_np.asarray(x, _np.float32))


def quantize_model(symbol, arg_params, calib=None, policy=None,
                   aux_params=None, name="model"):
    """Lower *symbol* onto the int8 kernels per *policy* and *calib*.

    Returns ``(qsym, qarg_params, qaux_params, report)``.  The report
    records per-layer coverage (``"int8"`` / ``"int8-weight-only"`` /
    ``"fp32:<reason>"`` for every Convolution/FullyConnected), the
    int8-passthrough ops, and the calib sha the lowering was built
    against — the identity ``health(name)`` and the tuning store
    quote.
    """
    policy = QuantizePolicy.coerce(policy if policy is not None
                                   else "int8")
    if policy is None:
        raise QuantizationError(
            "quantize_model needs an active policy (got 'off')")
    if policy.needs_calib:
        if calib is None:
            raise QuantizationError(
                "mode 'int8' quantizes activations and needs a "
                "CalibTable (run quantize.calibrate, or use "
                "'int8-weight-only')")
        if not isinstance(calib, CalibTable):
            raise QuantizationError(
                "calib must be a CalibTable, got %s"
                % type(calib).__name__)

    params_np = {n: _np_of(v) for n, v in (arg_params or {}).items()}
    order = symbol._topo()
    excluded = set(policy.exclude)
    qable = [n.name for n in order
             if not n.is_var and n.op.name in _QUANTIZABLE]
    skip_fl = set()
    if policy.first_last_fp32 and qable:
        skip_fl = {qable[0], qable[-1]}

    fp32 = {}     # (id(node), idx) -> entry producing the fp32 value
    qrep = {}     # (id(node), idx) -> (q, min, max entries, M float)
    acc32 = {}    # (id(node), idx) -> (int32, min, max entries)
    qargs = dict(arg_params or {})
    wq_cache = {}
    layers = {}
    passthrough = []

    def fp32_entry(key, src_name):
        """The fp32 entry for *key*, dequantizing a quantized-only
        tensor on demand (int32 accumulator preferred: full
        precision, bias already applied)."""
        e = fp32.get(key)
        if e is not None:
            return e
        if key in acc32:
            q, mn, mx = acc32[key]
        else:
            q, mn, mx = qrep[key][:3]
        deq = S._contrib_dequantize(
            Symbol([q]), Symbol([mn]), Symbol([mx]),
            name="%s_dequantize" % src_name)
        fp32[key] = deq._outputs[0]
        return fp32[key]

    def fp32_sym(entry_key, src):
        return Symbol([fp32_entry(entry_key, src)])

    def quant_weight(worig):
        """Offline symmetric int8 weight params (cached: tied weights
        quantize once)."""
        cached = wq_cache.get(worig.name)
        if cached is not None:
            return cached
        w = params_np[worig.name]
        m = float(_np.abs(w).max()) or 1e-8
        q = _np.clip(_np.round(w * 127.0 / m), -127, 127) \
            .astype(_np.int8)
        qargs["%s_quantized" % worig.name] = nd.array(q)
        qargs["%s_min" % worig.name] = _scalar(-m)
        qargs["%s_max" % worig.name] = _scalar(m)
        out = (S.var("%s_quantized" % worig.name),
               S.var("%s_min" % worig.name),
               S.var("%s_max" % worig.name), m)
        wq_cache[worig.name] = out
        return out

    def copy_fp32(node, reason=None):
        ins = [fp32_entry((id(s), i), tensor_name(s, i))
               for (s, i) in node.inputs]
        new = Node(node.op, node.name, params=node.params,
                   inputs=ins, attrs=node.attrs)
        for i in range(node.num_outputs()):
            fp32[(id(node), i)] = (new, i)
        if node.op.name in _QUANTIZABLE:
            layers[node.name] = "fp32:%s" % (reason or "fallback")

    for node in order:
        if node.is_var:
            fp32[(id(node), 0)] = (node, 0)
            continue
        opname = node.op.name
        lname = node.name
        key0 = (id(node), 0)
        in_node, in_idx = node.inputs[0] if node.inputs else (None, 0)
        ikey = (id(in_node), in_idx) if in_node is not None else None

        if opname in _QUANTIZABLE:
            # -- eligibility ----------------------------------------------
            reason = None
            if lname in excluded:
                reason = "excluded"
            elif lname in skip_fl:
                reason = "first-last-fp32"
            else:
                worig, _w_idx = node.inputs[1]
                if not (worig.is_var and worig.name in params_np):
                    reason = "weight-not-a-parameter"
            has_bias = not node.params.get("no_bias", False) and \
                len(node.inputs) > 2
            if reason is None and policy.mode == "int8":
                in_name = tensor_name(in_node, in_idx)
                if ikey not in qrep and not calib.covers(in_name):
                    reason = "no-calib-range"
                if reason is None and has_bias:
                    bsrc, _ = node.inputs[2]
                    if not (bsrc.is_var and bsrc.name in params_np):
                        reason = "bias-not-a-parameter"
            if reason is not None:
                copy_fp32(node, reason)
                continue

            wq_sym, wmin_sym, wmax_sym, m_w = quant_weight(
                node.inputs[1][0])

            if policy.mode == "int8-weight-only":
                # int8 weights shipped, dequantized in-graph; compute
                # stays fp32 (and so does the bias path)
                wdeq = S._contrib_dequantize(
                    wq_sym, wmin_sym, wmax_sym,
                    name="%s_wdeq" % lname)
                ins = [fp32_entry((id(s), i), tensor_name(s, i))
                       for (s, i) in node.inputs]
                ins[1] = wdeq._outputs[0]
                new = Node(node.op, lname, params=node.params,
                           inputs=ins, attrs=node.attrs)
                for i in range(node.num_outputs()):
                    fp32[(id(node), i)] = (new, i)
                layers[lname] = "int8-weight-only"
                continue

            # -- weight+activation int8 -----------------------------------
            if ikey in qrep:
                # fused: consume the upstream chain's int8 tensor
                q_e, mn_e, mx_e, m_in = qrep[ikey]
                d_sym = Symbol([q_e])
                dmn_sym, dmx_sym = Symbol([mn_e]), Symbol([mx_e])
            else:
                in_name = tensor_name(in_node, in_idx)
                m_in = calib.max_abs(in_name)
                qargs["%s_data_min" % lname] = _scalar(-m_in)
                qargs["%s_data_max" % lname] = _scalar(m_in)
                qz = S._contrib_quantize(
                    fp32_sym(ikey, in_name),
                    S.var("%s_data_min" % lname),
                    S.var("%s_data_max" % lname),
                    out_type="int8", name="%s_quantize" % lname)
                d_sym, dmn_sym, dmx_sym = qz[0], qz[1], qz[2]

            if opname == "Convolution":
                qp = {k: node.params[k] for k in _QCONV_PARAMS
                      if node.params.get(k) is not None}
                q = S._contrib_quantized_conv(
                    d_sym, wq_sym, dmn_sym, dmx_sym, wmin_sym,
                    wmax_sym, name="%s_quantized" % lname, **qp)
            else:
                qp = {k: node.params[k] for k in _QFC_PARAMS
                      if node.params.get(k) is not None}
                q = S._contrib_quantized_fully_connected(
                    d_sym, wq_sym, dmn_sym, dmx_sym, wmin_sym,
                    wmax_sym, name="%s_quantized" % lname, **qp)
            out32_sym, omn_sym, omx_sym = q[0], q[1], q[2]

            if has_bias:
                # bias at the accumulator scale, added in int32 so the
                # whole block (and any fused requantize) sees it
                b = params_np[node.inputs[2][0].name]
                s_acc = (m_in / 127.0) * (m_w / 127.0)
                bq = _np.clip(_np.round(b / s_acc),
                              -_INT32_MAX, _INT32_MAX) \
                    .astype(_np.int32)
                if opname == "Convolution":
                    rank = len(node.params.get("kernel", (1, 1)))
                    bq = bq.reshape((1, -1) + (1,) * rank)
                else:
                    bq = bq.reshape(1, -1)
                qargs["%s_bias_quantized" % lname] = nd.array(bq)
                out32_sym = S.broadcast_add(
                    out32_sym, S.var("%s_bias_quantized" % lname),
                    name="%s_biasadd" % lname)
            acc32[key0] = (out32_sym._outputs[0], omn_sym._outputs[0],
                           omx_sym._outputs[0])

            out_name = tensor_name(node, 0)
            if calib.covers(out_name):
                # fused inter-layer requantize: int32 -> int8 against
                # the calibrated range of THIS tensor, ready for the
                # next quantized consumer
                m_out = calib.max_abs(out_name)
                rq = S._contrib_requantize(
                    out32_sym, omn_sym, omx_sym,
                    min_calib_range=-m_out, max_calib_range=m_out,
                    name="%s_requantize" % lname)
                qrep[key0] = (rq._outputs[0], rq._outputs[1],
                              rq._outputs[2], m_out)
            layers[lname] = "int8"
            continue

        # -- int8-transparent ops: stay in the quantized domain ----------
        if policy.mode == "int8" and ikey in qrep and \
                lname not in excluded:
            q_e, mn_e, mx_e, m_in = qrep[ikey]
            qs = (Symbol([q_e]), Symbol([mn_e]), Symbol([mx_e]))
            handled = None
            if opname == "Activation" and \
                    node.params.get("act_type") == "relu":
                handled = S._contrib_quantized_act(
                    *qs, act_type="relu", name="%s_q" % lname)
            elif opname == "Pooling" and \
                    node.params.get("pool_type", "max") in \
                    ("max", "avg") and \
                    node.params.get("pooling_convention",
                                    "valid") == "valid":
                qp = {k: node.params[k]
                      for k in ("kernel", "stride", "pad",
                                "pool_type", "global_pool")
                      if node.params.get(k) is not None}
                handled = S._contrib_quantized_pooling(
                    *qs, name="%s_q" % lname, **qp)
            elif opname in ("Flatten", "flatten"):
                handled = S._contrib_quantized_flatten(
                    *qs, name="%s_q" % lname)
            if handled is not None:
                qrep[key0] = (handled._outputs[0],
                              handled._outputs[1],
                              handled._outputs[2], m_in)
                passthrough.append(lname)
                continue

        copy_fp32(node)

    qsym = Symbol([fp32_entry((id(n), i), tensor_name(n, i))
                   for (n, i) in symbol._outputs])
    live = set(qsym.list_arguments())
    qargs = {n: v for n, v in qargs.items() if n in live}
    aux_params = aux_params or {}
    qaux = {n: aux_params[n]
            for n in qsym.list_auxiliary_states() if n in aux_params}

    covered = sum(1 for v in layers.values()
                  if not v.startswith("fp32"))
    report = {
        "mode": policy.mode,
        "calib_sha": calib.sha if calib is not None else None,
        "layers": layers,
        "passthrough": passthrough,
        "covered": covered,
        "total": len(layers),
    }
    _obs_events.emit("quantize", kind="lower", model=name,
                     mode=policy.mode, covered=covered,
                     total=len(layers),
                     passthrough=len(passthrough),
                     calib_sha=(calib.sha[:12] if calib is not None
                                else None))
    return qsym, qargs, qaux, report


# -- lowered-HLO proof helpers ----------------------------------------------

def hlo_has_int8_compute(text):
    """Does the lowered StableHLO contain an int8 dot/conv?  The
    weight+activation gate: the MXU-eligible compute provably runs on
    int8 operands, not on dequantized fp32."""
    for line in text.splitlines():
        if ("dot_general" in line or "convolution" in line) and \
                ("xi8>" in line or "<i8>" in line):
            return True
    return False


def hlo_has_int8_tensors(text):
    """Weaker proof for weight-only mode: int8 tensors (the shipped
    weights) are present in the program at all."""
    return "xi8>" in text or "<i8>" in text
