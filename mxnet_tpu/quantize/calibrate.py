"""Profile-guided calibration: instrumented forward -> CalibTable.

Glow's recipe (PAPERS.md): run the fp32 graph on representative
traffic and capture every tensor's numeric range, then lower against
those ranges.  The capture here is **pure-JAX interception at the
op-registry boundary** — the same topological walk as
``executor._build_eval`` with a per-tensor ``min``/``max`` (or
percentile-of-|x|) reduction appended after each op call, all inside
ONE jitted program per batch shape.  No Python-level tracing hooks, no
monkeypatching of kernels, nothing a tracer can leak through
(graftlint-clean by construction).

The result is a :class:`CalibTable`: per-tensor symmetric-friendly
(min, max) ranges keyed by tensor name, with a sha256 identity over
the canonical payload.  Tables persist through the resilience layer's
``atomic_write`` and verify their sha on load — a torn or hand-edited
table fails typed (:class:`~mxnet_tpu.quantize.policy.QuantizationError`)
instead of quantizing a model against garbage ranges.
"""

from __future__ import annotations

import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as _np

from .policy import QuantizationError
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["CalibTable", "calibrate", "tensor_name"]

_CALIB_BATCHES_TOTAL = _obs_metrics.counter(
    "quant_calibration_batches_total",
    "calibration batches run through the instrumented forward")


def tensor_name(node, out_idx=0):
    """Canonical calibration key of a graph entry: the producing
    node's name, ``name:k`` for secondary outputs."""
    return node.name if out_idx == 0 else "%s:%d" % (node.name, out_idx)


def _build_collect(symbol, data_names, percentile=None):
    """The instrumented evaluation fn(arg_map, aux_map, key) ->
    {tensor name: (min, max)} — ``executor._build_eval`` in eval mode
    with a range reduction appended at the registry boundary."""
    order = symbol._topo()
    data_names = frozenset(data_names)
    csr_aware = ("dot", "cast_storage")

    def stat(v):
        if percentile is None:
            return jnp.min(v), jnp.max(v)
        m = jnp.percentile(jnp.abs(v).astype(jnp.float32).ravel(),
                           percentile)
        return -m, m

    def fn(arg_map, aux_map, key):
        from ..ops.sparse_graph import CsrCarrier
        vals = {}
        stats = {}
        for pos, node in enumerate(order):
            if node.is_var:
                v = arg_map[node.name] if node.name in arg_map \
                    else aux_map[node.name]
                vals[(id(node), 0)] = v
                if node.name in data_names and \
                        jnp.issubdtype(jnp.asarray(v).dtype,
                                       jnp.floating):
                    stats[node.name] = stat(v)
                continue
            op = node.op
            ins = [vals[(id(s), i)] for (s, i) in node.inputs]
            if op.name not in csr_aware:
                ins = [v.todense() if isinstance(v, CsrCarrier) else v
                       for v in ins]
            params = node.params
            if "training" in op.param_names:
                params = dict(params, training=False)
            if op.needs_rng:
                out = op.fn(jax.random.fold_in(key, pos), *ins,
                            **params)
            else:
                out = op.fn(*ins, **params)
            if not isinstance(out, tuple):
                out = (out,)
            for i, o in enumerate(out):
                vals[(id(node), i)] = o
                if hasattr(o, "dtype") and \
                        jnp.issubdtype(o.dtype, jnp.floating):
                    stats[tensor_name(node, i)] = stat(o)
        return stats

    return fn


class CalibTable(object):
    """Per-tensor calibrated ranges with a sha256 identity.

    ``ranges`` maps tensor name -> (min, max) floats.  The sha covers
    the canonical JSON payload (ranges + mode + percentile), so two
    tables with identical ranges share an identity and a corrupted
    file can never load silently.
    """

    VERSION = 1

    def __init__(self, ranges, mode="minmax", percentile=None,
                 batches=0):
        self.ranges = {str(n): (float(lo), float(hi))
                       for n, (lo, hi) in ranges.items()}
        self.mode = str(mode)
        self.percentile = None if percentile is None \
            else float(percentile)
        self.batches = int(batches)

    # -- identity ----------------------------------------------------------
    def payload(self):
        return {"version": self.VERSION, "mode": self.mode,
                "percentile": self.percentile, "batches": self.batches,
                "ranges": {n: [lo, hi] for n, (lo, hi)
                           in sorted(self.ranges.items())}}

    @property
    def sha(self):
        blob = json.dumps(self.payload(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    # -- lookups -----------------------------------------------------------
    def covers(self, name):
        return name in self.ranges

    def range(self, name):
        return self.ranges.get(name)

    def max_abs(self, name):
        """Symmetric magnitude M of a tensor's range (real = q*M/127),
        floored away from zero so a dead tensor cannot divide by 0."""
        lo, hi = self.ranges[name]
        return max(abs(lo), abs(hi)) or 1e-8

    def __len__(self):
        return len(self.ranges)

    # -- persistence (resilience layer: atomic, sha-verified) --------------
    def save(self, path):
        from ..resilience.checkpoint import atomic_write
        blob = json.dumps({"calib_table": self.payload(),
                           "sha": self.sha},
                          sort_keys=True, indent=1).encode()
        atomic_write(path, blob)
        return self.sha

    @classmethod
    def load(cls, path):
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode())
            payload = doc["calib_table"]
            table = cls(
                {n: tuple(v) for n, v in payload["ranges"].items()},
                mode=payload["mode"],
                percentile=payload.get("percentile"),
                batches=payload.get("batches", 0))
            stored = doc["sha"]
        except QuantizationError:
            raise
        except Exception as exc:
            raise QuantizationError(
                "calibration table %r is unreadable: %s: %s"
                % (path, type(exc).__name__, exc))
        if table.sha != stored:
            raise QuantizationError(
                "calibration table %r failed its sha check "
                "(stored %s != computed %s) — refusing to quantize "
                "against corrupted ranges"
                % (path, stored[:12], table.sha[:12]))
        return table


def calibrate(symbol, arg_params, batches, aux_params=None,
              mode="minmax", percentile=99.99, data_names=None,
              name="model"):
    """Run the instrumented forward over *batches* and return a
    :class:`CalibTable` covering every floating intermediate tensor.

    Parameters
    ----------
    symbol : Symbol
        The fp32 inference graph.
    arg_params : dict name -> array
        Model parameters (anything the symbol's arguments need beyond
        the data inputs).
    batches : iterable
        Calibration batches: dicts ``{input name: array}``, or bare
        arrays for single-input models.
    mode : "minmax" | "percentile"
        Global min/max over all batches, or the per-batch
        *percentile* of |x| (outlier-robust), aggregated by max.
    """
    if mode not in ("minmax", "percentile"):
        raise QuantizationError(
            "calibration mode must be 'minmax' or 'percentile', "
            "got %r" % (mode,))
    pct = float(percentile) if mode == "percentile" else None
    params = {}
    for n, v in (arg_params or {}).items():
        data = getattr(v, "_data", None)
        params[n] = data if data is not None else jnp.asarray(v)
    aux = {}
    for n, v in (aux_params or {}).items():
        data = getattr(v, "_data", None)
        aux[n] = data if data is not None else jnp.asarray(v)
    if data_names is None:
        data_names = [n for n in symbol.list_arguments()
                      if n not in params]
    data_names = list(data_names)
    collect = jax.jit(_build_collect(symbol, data_names,
                                     percentile=pct))
    key = jax.random.PRNGKey(0)

    agg = {}
    n_batches = 0
    for batch in batches:
        if not isinstance(batch, dict):
            if len(data_names) != 1:
                raise QuantizationError(
                    "calibration batches must be dicts for a model "
                    "with %d data inputs %s"
                    % (len(data_names), sorted(data_names)))
            batch = {data_names[0]: batch}
        feeds = {}
        for dn in data_names:
            if dn not in batch:
                raise QuantizationError(
                    "calibration batch is missing input %r" % dn)
            v = batch[dn]
            data = getattr(v, "_data", None)
            feeds[dn] = data if data is not None else jnp.asarray(v)
        stats = collect(dict(params, **feeds), aux, key)
        for tname, (lo, hi) in stats.items():
            lo = float(lo)
            hi = float(hi)
            cur = agg.get(tname)
            if cur is None:
                agg[tname] = (lo, hi)
            else:
                agg[tname] = (min(cur[0], lo), max(cur[1], hi))
        n_batches += 1
        _CALIB_BATCHES_TOTAL.inc()
    if not n_batches:
        raise QuantizationError(
            "calibration needs at least one batch (model %r)" % name)
    table = CalibTable(agg, mode=mode, percentile=pct,
                       batches=n_batches)
    _obs_events.emit("quantize", kind="calibrate", model=name,
                     mode=mode, batches=n_batches, tensors=len(table),
                     sha=table.sha[:12])
    return table
