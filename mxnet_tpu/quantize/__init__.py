"""Post-training int8 quantization for the serving path.

The graph-level pipeline ROADMAP item 3 asks for, in three stages:

1. :func:`calibrate` — instrumented fp32 forward over representative
   batches capturing per-tensor ranges into a sha-identified
   :class:`CalibTable` (atomic save, sha-verified load).
2. :func:`quantize_model` — lower Convolution/FullyConnected (and the
   int8-transparent ops between them) onto the ``_contrib_quantized_*``
   kernels with fused inter-layer requantize, offline int8 weights,
   int32 bias folding and fp32 fallback, under a
   :class:`QuantizePolicy`.
3. Serving integration — ``ModelRegistry.load(..., quantize=...)``
   builds the quantized rungs through the normal BucketLadder/warm
   path and gates accuracy vs fp32 at load time (failures raise
   :class:`QuantizationError`; see ``mxnet_tpu/serve/registry.py``).

See docs/quantization.md for the workflow.
"""

from .calibrate import CalibTable, calibrate, tensor_name
from .lower import (hlo_has_int8_compute, hlo_has_int8_tensors,
                    quantize_model)
from .policy import MODES, QuantizationError, QuantizePolicy

__all__ = [
    "CalibTable",
    "MODES",
    "QuantizationError",
    "QuantizePolicy",
    "calibrate",
    "hlo_has_int8_compute",
    "hlo_has_int8_tensors",
    "quantize_model",
    "tensor_name",
]
