"""Learning-rate schedules.

API parity with the reference's ``python/mxnet/lr_scheduler.py``
(FactorScheduler, MultiFactorScheduler, PolyScheduler, CosineScheduler,
linear/constant warmup), but designed differently: every schedule here
is a *pure function* of ``num_update`` evaluated against the current
``base_lr`` attribute, instead of a stateful object that mutates its
own learning rate as a side effect of being called.  Pure schedules are
idempotent (calling twice with the same step returns the same value),
safe to evaluate out of order (e.g. when resuming from a checkpoint),
and trivially liftable into a jitted update step as a traced scalar.

``base_lr`` remains a plain assignable attribute because the optimizer
overwrites it with its own ``learning_rate`` at attach time.
"""

from __future__ import annotations

import bisect
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    """Base class: handles the optional warmup ramp, then delegates the
    post-warmup value to :meth:`schedule`."""

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        if warmup_mode not in ("linear", "constant"):
            raise ValueError("unknown warmup_mode %r" % (warmup_mode,))
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_mode = warmup_mode

    # Kept for reference-API compatibility; some callers poke this.
    @property
    def warmup_final_lr(self):
        return self.base_lr

    def get_warmup_lr(self, num_update):
        if num_update >= self.warmup_steps:
            raise ValueError("get_warmup_lr called past warmup")
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        frac = num_update / self.warmup_steps
        return self.warmup_begin_lr + frac * (self.base_lr -
                                              self.warmup_begin_lr)

    def schedule(self, num_update):
        """Post-warmup learning rate at ``num_update`` (pure)."""
        raise NotImplementedError

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self.schedule(num_update)


class FactorScheduler(LRScheduler):
    """Multiply the lr by ``factor`` once every ``step`` updates, never
    going below ``stop_factor_lr``."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 **kw):
        super().__init__(base_lr, **kw)
        if step < 1:
            raise ValueError("step must be >= 1")
        if not 0.0 < factor <= 1.0:
            raise ValueError("factor must be in (0, 1]")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def schedule(self, num_update):
        # the k-th decay fires when num_update first exceeds k*step
        decays = max(0, (num_update - 1)) // self.step
        if decays == 0:
            # the floor only applies to DECAYED values: a base_lr
            # configured below stop_factor_lr must not be raised
            return self.base_lr
        return max(self.base_lr * self.factor ** decays,
                   self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """Multiply the lr by ``factor`` as each boundary in ``step`` (a
    sorted list of update counts) is passed."""

    def __init__(self, step, factor=1, base_lr=0.01, **kw):
        super().__init__(base_lr, **kw)
        if not step or list(step) != sorted(step):
            raise ValueError("step must be a non-empty sorted list")
        if not 0.0 < factor <= 1.0:
            raise ValueError("factor must be in (0, 1]")
        self.step = list(step)
        self.factor = factor

    def schedule(self, num_update):
        # boundary b has been passed once num_update > b
        decays = bisect.bisect_left(self.step, num_update)
        return self.base_lr * self.factor ** decays


class _DecayToFinal(LRScheduler):
    """Shared shape for schedules that anneal base_lr -> final_lr over
    ``max_update`` total updates (warmup included in the count)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0, **kw):
        super().__init__(base_lr, **kw)
        if max_update <= self.warmup_steps:
            raise ValueError("max_update must exceed warmup_steps")
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - self.warmup_steps

    def _progress(self, num_update):
        """Fraction of the decay phase completed, clamped to [0, 1]."""
        done = num_update - self.warmup_steps
        return min(max(done / self.max_steps, 0.0), 1.0)

    def _anneal(self, frac):
        raise NotImplementedError

    def schedule(self, num_update):
        span = self.base_lr - self.final_lr
        return self.final_lr + span * self._anneal(
            self._progress(num_update))


class PolyScheduler(_DecayToFinal):
    """Polynomial decay: remaining fraction ``(1 - t)**pwr``."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0, **kw):
        super().__init__(max_update, base_lr, final_lr, **kw)
        self.power = pwr

    def _anneal(self, frac):
        return (1.0 - frac) ** self.power


class CosineScheduler(_DecayToFinal):
    """Half-cosine decay: remaining fraction ``(1 + cos(pi t)) / 2``."""

    def _anneal(self, frac):
        return 0.5 * (1.0 + math.cos(math.pi * frac))
