"""Pure-functional tree-level optimizer layer for the fused train step.

The per-parameter ``Optimizer.update`` path dispatches one eager XLA
computation per parameter per step (optimizer.py ``_fused``) — ~160
host round trips for a ResNet-50.  This module maps the SAME fused
update kernels (ops/optimizer_ops.py) over a whole parameter pytree
INSIDE one traced program, so ``Executor.init_fused_step`` can fold
forward + backward + gradient reduction + optimizer update into a
single donated ``jax.jit`` (SURVEY §L2: the dependency engine
collapses into XLA async dispatch — now including the update).

Contract with the legacy layer (optimizer.py):

* state trees reuse ``Optimizer.create_state_multi_precision`` per
  index, so the structure per parameter is EXACTLY the legacy
  ``Updater.states[index]`` nesting — ``export_to_updater`` /
  ``import_from_updater`` convert by rebinding array handles only (no
  copies), which is what makes optimizer-state checkpoints round-trip
  bit-exact across the fused/legacy boundary.
* per-step scalars (lr after scheduler + multipliers + Adam's bias
  correction, wd after multipliers, the shared update count t) are
  resolved HOST-side by ``host_hyper`` with the same code the legacy
  loop runs (``_bump``/``_get_lr``/``_get_wd``), then enter the jit
  as traced scalars — bit-identical hyper-parameters, and no
  recompiles when the scheduler moves lr.
* row-sparse ``(ids, vals)`` gradient pairs from the executor's
  sparse-Embedding path get the functional mirror of the eager lazy
  row updates (ndarray/sparse.py ``*_row_update``): out-of-bounds
  padding ids drop out of ``.at[]`` scatters exactly like the eager
  path, so only touched rows see the update (and its weight decay).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from . import optimizer as _opt

__all__ = ["supports_fused", "host_hyper", "hyper_sig",
           "init_tree_state", "tree_update", "make_tree_update",
           "to_device_tree", "tree_to_nd", "export_to_updater",
           "import_from_updater", "nonfinite_any", "select_tree",
           "guarded_tree_update"]

# every hyper-param any builder bakes into the compiled program as a
# Python constant (lr/wd/t are NOT here — they enter as traced
# scalars).  The legacy Updater loop re-reads these from the optimizer
# every step, so Module re-checks this signature per fused step and
# rebuilds on mutation (e.g. rescale_grad reset after a batch-size
# change) instead of silently applying the stale baked value.
_HYPER_ATTRS = ("rescale_grad", "clip_gradient", "momentum",
                "lazy_update", "multi_precision", "wd_lh", "gamma1",
                "gamma2", "epsilon", "centered", "clip_weights",
                "beta1", "beta2", "rho", "lamda1", "beta",
                "schedule_decay", "float_stable_eps")


def hyper_sig(optimizer):
    """Snapshot of the build-time-baked hyper-params (see
    ``_HYPER_ATTRS``); compare across steps to detect mid-run
    mutation."""
    return tuple(getattr(optimizer, a, None) for a in _HYPER_ATTRS)


def _get_op(name):
    from ..ops.registry import get_op
    return get_op(name)


def _is_arr(x):
    return hasattr(x, "dtype") and hasattr(x, "shape")


def _is_rsp(g):
    """Executor sparse-Embedding grads arrive as (ids, vals) pairs."""
    return isinstance(g, tuple) and len(g) == 2


def _knobs(opt, op):
    """Static rescale/clip knobs, honoring ftml's clip_grad spelling
    (mirrors Optimizer._common_knobs + FTML.update)."""
    kw = {"rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        key = "clip_grad" if "clip_grad" in op.param_names \
            else "clip_gradient"
        kw[key] = opt.clip_gradient
    return kw


def _densify_pair(g, shape):
    """(ids, vals) -> dense grad; out-of-bounds padding ids drop."""
    ids, vals = g
    out = jnp.zeros(shape, vals.dtype)
    return out.at[ids.astype(jnp.int32)].add(vals)


def _rsp_prep(w, ids, vals, rescale, clip, wd):
    """Functional mirror of ndarray/sparse.py _prep_row_grad: gather
    touched rows, rescale/clip, add wd on those rows only.  wd is a
    traced scalar here so it is applied unconditionally (identical
    when wd == 0)."""
    rows = ids.astype(jnp.int32)
    g = vals * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    g = g + wd * w[rows]
    return rows, g


# -- per-class update builders ----------------------------------------------
# Each builder returns upd(w, g, state, lr, wd, t) -> (new_w, new_state)
# preserving the exact legacy state nesting for that class.


def _make_sgd(opt):
    mom = opt.momentum
    kn = _knobs(opt, _get_op("sgd_update"))
    rescale = kn["rescale_grad"]
    clip = kn.get("clip_gradient")

    def upd(w, g, state, lr, wd, t):
        # SGD's own mp check is structural (optimizer.py
        # update_multi_precision): (mom_or_None, f32 master) pair
        is_mp = (isinstance(state, tuple) and len(state) == 2
                 and _is_arr(state[1])
                 and state[1].dtype == jnp.float32
                 and w.dtype != jnp.float32)
        if _is_rsp(g):
            if opt.lazy_update and not is_mp:
                ids, vals = g
                rows, gr = _rsp_prep(w, ids, vals, rescale, clip, wd)
                if state is None:
                    return w.at[rows].add((-lr * gr).astype(w.dtype)), None
                m_rows = mom * state[rows] - lr * gr
                new_m = state.at[rows].set(m_rows.astype(state.dtype))
                return w.at[rows].add(m_rows.astype(w.dtype)), new_m
            g = _densify_pair(g, w.shape)
        if is_mp:
            m, w32 = state
            if m is not None:
                nw, nm, nw32 = _get_op("mp_sgd_mom_update").fn(
                    w, g, m, w32, lr=lr, momentum=mom, wd=wd, **kn)
                return nw, (nm, nw32)
            nw, nw32 = _get_op("mp_sgd_update").fn(
                w, g, w32, lr=lr, wd=wd, **kn)
            return nw, (None, nw32)
        if state is not None:
            nw, nm = _get_op("sgd_mom_update").fn(
                w, g, state, lr=lr, momentum=mom, wd=wd, **kn)
            return nw, nm
        return _get_op("sgd_update").fn(w, g, lr=lr, wd=wd, **kn), None

    return upd


def _make_adagrad(opt):
    eps = opt.float_stable_eps
    op = _get_op("_sparse_adagrad_update")
    kn = _knobs(opt, op)
    rescale = kn["rescale_grad"]
    clip = kn.get("clip_gradient")

    def upd(w, g, state, lr, wd, t):
        if _is_rsp(g):
            # mirror of sparse.py adagrad_row_update (always lazy)
            ids, vals = g
            rows, gr = _rsp_prep(w, ids, vals, rescale, clip, wd)
            h_rows = state[rows] + jnp.square(gr)
            new_h = state.at[rows].set(h_rows.astype(state.dtype))
            nw = w.at[rows].add(
                (-lr * gr / (jnp.sqrt(h_rows) + eps)).astype(w.dtype))
            return nw, new_h
        nw, nh = op.fn(w, g, state, lr=lr, epsilon=eps, wd=wd, **kn)
        return nw, nh

    return upd


def _make_simple(op_name, static_of, needs_t=False):
    """Builder for optimizers that are one dense kernel call.  The
    state nesting in == nesting out: None, a single array, or a tuple,
    exactly as create_state built it."""

    def make(opt):
        op = _get_op(op_name)
        hyper = dict(static_of(opt))
        hyper.update(_knobs(opt, op))
        takes_lr = "lr" in op.param_names

        def upd(w, g, state, lr, wd, t):
            if _is_rsp(g):
                g = _densify_pair(g, w.shape)
            states = state if isinstance(state, tuple) \
                else (() if state is None else (state,))
            kw = dict(hyper, wd=wd)
            if takes_lr:
                kw["lr"] = lr
            if needs_t:
                kw["t"] = t
            out = op.fn(w, g, *states, **kw)
            out = out if isinstance(out, tuple) else (out,)
            if isinstance(state, tuple):
                return out[0], tuple(out[1:])
            if state is None:
                return out[0], None
            return out[0], out[1]

        return upd

    return make


def _per_state(mom_make, plain_make):
    """Legacy NAG/Signum pick the kernel per UPDATE from ``state is
    not None``, not from the momentum hyper-param — mirror that, so a
    momentum raised from 0 mid-run (hyper rebuild) keeps treating the
    existing None states momentumless instead of crashing."""

    def make(opt):
        mom_upd, plain_upd = mom_make(opt), plain_make(opt)

        def upd(w, g, state, lr, wd, t):
            if state is None:
                return plain_upd(w, g, None, lr, wd, t)
            return mom_upd(w, g, state, lr, wd, t)

        return upd

    return make


_make_nag = _per_state(
    _make_simple("nag_mom_update", lambda o: {"momentum": o.momentum}),
    _make_simple("sgd_update", lambda o: {}))


_make_signum = _per_state(
    _make_simple("signum_update",
                 lambda o: {"momentum": o.momentum, "wd_lh": o.wd_lh}),
    _make_simple("signsgd_update", lambda o: {}))


def _make_rmsprop(opt):
    extra = {"clip_weights": opt.clip_weights} if opt.clip_weights else {}
    if opt.centered:
        return _make_simple(
            "rmspropalex_update",
            lambda o: dict(gamma1=o.gamma1, gamma2=o.gamma2,
                           epsilon=o.epsilon, **extra))(opt)
    return _make_simple(
        "rmsprop_update",
        lambda o: dict(gamma1=o.gamma1, epsilon=o.epsilon, **extra))(opt)


_BUILDERS = {
    _opt.SGD: _make_sgd,
    _opt.AdaGrad: _make_adagrad,
    _opt.NAG: _make_nag,
    _opt.Signum: _make_signum,
    _opt.SignSGD: _make_signum,
    _opt.RMSProp: _make_rmsprop,
    _opt.Adam: _make_simple(
        "adam_update",
        lambda o: dict(beta1=o.beta1, beta2=o.beta2, epsilon=o.epsilon)),
    _opt.AdaDelta: _make_simple(
        "adadelta_update", lambda o: dict(rho=o.rho, epsilon=o.epsilon)),
    _opt.Ftrl: _make_simple(
        "ftrl_update", lambda o: dict(lamda1=o.lamda1, beta=o.beta)),
    _opt.Adamax: _make_simple(
        "adamax_update", lambda o: dict(beta1=o.beta1, beta2=o.beta2),
        needs_t=True),
    _opt.Nadam: _make_simple(
        "nadam_update",
        lambda o: dict(beta1=o.beta1, beta2=o.beta2, epsilon=o.epsilon,
                       schedule_decay=o.schedule_decay), needs_t=True),
    _opt.FTML: _make_simple(
        "ftml_update",
        lambda o: dict(beta1=o.beta1, beta2=o.beta2, epsilon=o.epsilon),
        needs_t=True),
}


def supports_fused(optimizer):
    """True when *optimizer* maps onto the tree kernels.  Exact class
    match on purpose: a subclass overriding ``update`` (LBSGD's LARS
    host readbacks, DCASGD, SGLD's rng) must keep the legacy loop."""
    return type(optimizer) in _BUILDERS


def _with_generic_mp(opt, upd):
    """Mirror of Optimizer.update_multi_precision's generic fp32-master
    fallback: update the master, cast down."""

    def wrapped(w, g, state, lr, wd, t):
        is_mp = (opt.multi_precision and isinstance(state, tuple)
                 and len(state) == 2 and _is_arr(state[1])
                 and state[1].dtype == jnp.float32
                 and w.dtype != jnp.float32)
        if not is_mp:
            return upd(w, g, state, lr, wd, t)
        inner, w32 = state
        if _is_rsp(g):
            g = (g[0], g[1].astype(jnp.float32))
        else:
            g = g.astype(jnp.float32)
        nw32, ninner = upd(w32, g, inner, lr, wd, t)
        return nw32.astype(w.dtype), (ninner, nw32)

    return wrapped


def make_tree_update(optimizer):
    """Build the pure fn(grads, params, state, lrs, wds, t) ->
    (new_params, new_state) mapping the optimizer's kernel over a
    name-keyed param pytree with per-name lr/wd scalars."""
    try:
        upd = _BUILDERS[type(optimizer)](optimizer)
    except KeyError:
        raise ValueError(
            "optimizer %r has no tree-level kernel mapping; the fused "
            "train step supports %s"
            % (type(optimizer).__name__,
               sorted(c.__name__ for c in _BUILDERS)))
    if type(optimizer) is not _opt.SGD:
        upd = _with_generic_mp(optimizer, upd)

    def tree_update_fn(grads, params, state, lrs, wds, ts):
        new_p, new_s = {}, {}
        for n in params:
            new_p[n], new_s[n] = upd(params[n], grads[n], state[n],
                                     lrs[n], wds[n], ts[n])
        return new_p, new_s

    return tree_update_fn


# -- non-finite guard (resilience subsystem) --------------------------------
# One in-graph isfinite reduction over the loss+grad tree decides
# whether the optimizer update applies; on a bad step the params and
# state pass through BIT-IDENTICAL (jnp.where with a scalar predicate
# is a bitwise select).  Everything stays inside the enclosing jit —
# no extra dispatch, no recompile (the predicate is a traced value).


def nonfinite_any(tree):
    """Scalar bool: True when any inexact-dtype leaf of *tree* holds a
    NaN/Inf.  Integer leaves (rsp row ids, counters) are finite by
    construction and skipped; non-array leaves are ignored.  XLA fuses
    the per-leaf reductions into the surrounding program."""
    import jax
    bad = jnp.asarray(False)
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and \
                jnp.issubdtype(leaf.dtype, jnp.inexact):
            bad = jnp.logical_or(
                bad, jnp.logical_not(jnp.all(jnp.isfinite(leaf))))
    return bad


def select_tree(pred, if_true, if_false):
    """Per-leaf ``where(pred, t, f)`` over two same-structure trees
    (None subtrees pass through).  With a False predicate the result
    is bit-identical *if_false*, with True bit-identical *if_true* —
    which is what lets a skipped step leave weights and optimizer
    state untouched down to the last bit."""
    import jax
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f), if_true, if_false)


def guarded_tree_update(tree_update_fn):
    """Wrap a tree-update sweep with the non-finite guard: returns
    ``fn(grads, params, state, lrs, wds, ts) -> (new_params,
    new_state, skipped)`` where *skipped* is an int32 0/1.  On a bad
    step params/state pass through bit-identical."""

    def guarded(grads, params, state, lrs, wds, ts):
        bad = nonfinite_any(grads)
        new_p, new_s = tree_update_fn(grads, params, state, lrs, wds, ts)
        new_p = select_tree(bad, params, new_p)
        new_s = select_tree(bad, state, new_s)
        return new_p, new_s, bad.astype(jnp.int32)

    return guarded


def tree_update(optimizer, step, grads, params, state, lrs=None,
                wds=None):
    """One functional optimizer sweep over a param tree (the direct
    API; the executor's fused step closes over make_tree_update
    instead).  *step* is the update count t applied to every name;
    *lrs*/*wds* default to the optimizer's current flat lr/wd —
    including Adam's in-lr bias correction at t=step, matching the
    legacy Updater and host_hyper."""
    if lrs is None:
        lr = optimizer.learning_rate
        if type(optimizer) is _opt.Adam:
            lr = lr * math.sqrt(1.0 - optimizer.beta2 ** step) / \
                (1.0 - optimizer.beta1 ** step)
        lrs = {n: lr for n in params}
    if wds is None:
        wds = {n: optimizer.wd for n in params}
    return make_tree_update(optimizer)(grads, params, state, lrs, wds,
                                       {n: step for n in params})


def host_hyper(optimizer, names, idx_of):
    """Advance the per-index update counts and resolve this step's
    per-parameter (t, lr, wd) exactly like one legacy update sweep —
    each index keeps its OWN count (they diverge e.g. when an optimizer
    is shared across modules), and Adam's in-lr bias correction uses
    that per-index count with the same host-side math.  Returns
    (ts, lrs, wds), name-keyed dicts of Python scalars (they enter the
    jit as traced weak-typed scalars, so no recompiles as they move).
    One caveat vs the legacy loop: a scheduler-driven lr is resolved
    AFTER all counts advanced, while the legacy loop ratchets
    num_update mid-sweep — identical whenever the counts are uniform,
    which every pure fused/legacy training run keeps them."""
    ts, lrs, wds = {}, {}, {}
    for n in names:
        ts[n] = optimizer._bump(idx_of[n])
    adam = type(optimizer) is _opt.Adam
    for n in names:
        i = idx_of[n]
        lr = optimizer._get_lr(i)
        if adam:
            t = ts[n]
            lr = lr * math.sqrt(1.0 - optimizer.beta2 ** t) / \
                (1.0 - optimizer.beta1 ** t)
        lrs[n] = lr
        wds[n] = optimizer._get_wd(i)
    return ts, lrs, wds


# -- state trees and legacy Updater interop ---------------------------------


def to_device_tree(s, put=None):
    """Legacy state nesting (NDArray/tuple/None) -> jax-array nesting,
    rebinding handles (optionally placing via *put*)."""
    from ..ndarray import NDArray
    if isinstance(s, NDArray):
        return put(s._data) if put is not None else s._data
    if isinstance(s, (tuple, list)):
        return tuple(to_device_tree(x, put) for x in s)
    if _is_arr(s):
        return put(s) if put is not None else s
    return s


def tree_to_nd(s):
    """jax-array nesting -> the legacy NDArray nesting Updater stores."""
    from ..ndarray import NDArray
    if _is_arr(s):
        return NDArray(s)
    if isinstance(s, (tuple, list)):
        return tuple(tree_to_nd(x) for x in s)
    return s


def init_tree_state(optimizer, params, idx_of=None, put=None):
    """Fresh per-name state trees via the legacy
    ``create_state_multi_precision`` (identical nesting and zeros)."""
    state = {}
    for n, w in params.items():
        i = idx_of[n] if idx_of is not None else n
        state[n] = to_device_tree(
            optimizer.create_state_multi_precision(i, w), put)
    return state


def import_from_updater(updater, optimizer, params, idx_of, put=None):
    """Updater.states (legacy per-index format) -> name-keyed tree,
    creating fresh state for indices the updater has not seen — the
    lazy-create contract of Updater.__call__."""
    state = {}
    for n, w in params.items():
        i = idx_of[n]
        if i in updater.states:
            state[n] = to_device_tree(updater.states[i], put)
        else:
            state[n] = to_device_tree(
                optimizer.create_state_multi_precision(i, w), put)
    return state


def export_to_updater(tree_state, updater, idx_of, copy=False):
    """Name-keyed tree -> Updater.states in the exact legacy per-index
    format, so ``Updater.get_states()`` (and save_optimizer_states)
    serializes the fused state.  With *copy* (donating backends) the
    arrays are copied: a handle-rebound alias of the live tree would be
    deleted by the next fused step's donation — the mirror of the copy
    ``import_from_updater`` callers make on the way in."""

    def conv(s):
        if _is_arr(s):
            return jnp.array(s) if copy else s
        if isinstance(s, (tuple, list)):
            return tuple(conv(x) for x in s)
        return s

    for n, s in tree_state.items():
        i = idx_of[n]
        updater.states[i] = tree_to_nd(conv(s))
        updater.states_synced[i] = True
