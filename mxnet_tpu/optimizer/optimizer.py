"""Optimizers (reference: python/mxnet/optimizer/optimizer.py, 1,571 LoC).

Design: every ``update`` resolves its per-parameter hyper-parameters
(lr/wd multipliers, update count) in Python and then dispatches ONE
fused update op from ``mxnet_tpu/ops/optimizer_ops.py`` — a single XLA
computation per parameter with the weight/state buffers donated, the
TPU analogue of the reference's fused optimizer kernels
(src/operator/optimizer_op.cc:43-651).  The shared ``_fused`` helper
owns the out-list/common-kwarg plumbing so each optimizer subclass is
just its hyper-parameters plus one dispatch line.  ``Updater``
reproduces the serializable per-index state store that KVStore servers
run (reference optimizer.py:1504).
"""

from __future__ import annotations

import math
import pickle

import numpy as _np

from ..base import registry as _registry
from .. import ndarray as nd
from ..ndarray import NDArray

_reg = _registry("optimizer")

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "FTML", "LBSGD",
           "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test", "Updater",
           "create", "register", "get_updater", "states_mismatch"]


def register(klass):
    _reg.register(klass)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _reg.get(name)(**kwargs)


_LOW_PRECISION = ("float16", "bfloat16")


class Optimizer:
    """Base optimizer (reference: optimizer.py Optimizer:46).

    Subclass contract: implement ``create_state`` (None or a tuple of
    state NDArrays per parameter) and ``update``; use ``_bump`` to get
    the per-parameter step count and ``_fused`` to dispatch the kernel.
    """

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None):
        self.lr, self.wd = learning_rate, wd
        self.rescale_grad, self.clip_gradient = rescale_grad, clip_gradient
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            lr_scheduler.base_lr = learning_rate
        self.begin_num_update = self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult, self.wd_mult = {}, {}

    # -- per-parameter hyper-parameter resolution -------------------------

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("learning rate is owned by the attached "
                              "LRScheduler")
        self.lr = lr

    @property
    def learning_rate(self):
        sched = self.lr_scheduler
        return self.lr if sched is None else sched(self.num_update)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        # decay applies to weights and BN scales; bias/beta/aux get 0
        # unless explicitly overridden (reference set_wd_mult semantics)
        self.wd_mult = {n: 0.0 for n in self.idx2name.values()
                        if not n.endswith(("_weight", "_gamma"))}
        self.wd_mult.update(args_wd_mult)

    def _multiplier(self, index, table):
        """Multiplier for *index* from a {index-or-name: mult} table,
        honoring Parameter objects in param_dict first."""
        if index in self.param_dict:
            p = self.param_dict[index]
            return p.lr_mult if table is self.lr_mult else p.wd_mult
        if index in table:
            return table[index]
        return table.get(self.idx2name.get(index), 1.0)

    def _get_lr(self, index):
        return self.learning_rate * self._multiplier(index, self.lr_mult)

    def _get_wd(self, index):
        return self.wd * self._multiplier(index, self.wd_mult)

    def _bump(self, index):
        """Advance and return this parameter's update count."""
        t = self._index_update_count.get(index,
                                         self.begin_num_update) + 1
        self._index_update_count[index] = t
        self.num_update = max(t, self.num_update)
        return t

    # kept under the reference's internal name: subclasses there call it
    _update_count = _bump

    # -- state ------------------------------------------------------------

    def create_state(self, index, weight):
        return None

    def _master_copy(self, index, weight):
        """(state, fp32 master) pair when mp applies, else plain state."""
        if self.multi_precision and str(weight.dtype) in _LOW_PRECISION:
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    create_state_multi_precision = _master_copy

    # -- dispatch ---------------------------------------------------------

    def _common_knobs(self):
        """The knobs every fused/sparse update kernel takes."""
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def _fused(self, op_name, weight, grad, states=(), **hyper):
        """Run one fused update kernel: outputs alias [weight, *states],
        common knobs (rescale/clip) merged in."""
        for k, v in self._common_knobs().items():
            hyper.setdefault(k, v)
        bufs = [weight] + [s for s in states if s is not None]
        getattr(nd, op_name)(
            weight, grad, *[s for s in states if s is not None],
            out=bufs if len(bufs) > 1 else weight, **hyper)

    def _densify(self, grad):
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.BaseSparseNDArray):
            return grad.todense()
        return grad

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        is_mp = (self.multi_precision and isinstance(state, tuple)
                 and isinstance(state[-1], NDArray)
                 and state[-1].dtype == _np.float32
                 and weight.dtype != _np.float32)
        if not is_mp:
            return self.update(index, weight, grad, state)
        # generic fp32-master fallback: update the master, cast down
        inner, w32 = state
        self.update(index, w32, grad.astype("float32"), inner)
        weight._data = w32._data.astype(weight._data.dtype)


# ---------------------------------------------------------------------------


@register
class SGD(Optimizer):
    """SGD with momentum, lazy row-sparse updates, and fused
    multi-precision kernels (reference: optimizer.py SGD:451)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lazy_update = momentum, lazy_update

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype)) \
            if self.momentum else None

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.RowSparseNDArray) and self.lazy_update:
            # only the gradient's rows are touched (reference:
            # optimizer_op.cc sgd row_sparse lazy_update)
            kw = self._common_knobs()
            if state is not None:
                _sp.sgd_mom_row_update(weight, grad, state, lr=lr,
                                       momentum=self.momentum, wd=wd,
                                       **kw)
            else:
                _sp.sgd_row_update(weight, grad, lr=lr, wd=wd, **kw)
            return
        grad = self._densify(grad)
        if state is not None:
            self._fused("sgd_mom_update", weight, grad, (state,),
                        lr=lr, wd=wd, momentum=self.momentum)
        else:
            self._fused("sgd_update", weight, grad, lr=lr, wd=wd)

    def update_multi_precision(self, index, weight, grad, state):
        is_mp = (isinstance(state, tuple)
                 and isinstance(state[1], NDArray)
                 and state[1].dtype == _np.float32
                 and weight.dtype != _np.float32)
        if not is_mp:
            return self.update(index, weight, grad, state)
        # fused mp kernels are dense-only: correctness over laziness
        grad = self._densify(grad)
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, w32 = state
        if mom is not None:
            self._fused("mp_sgd_mom_update", weight, grad, (mom, w32),
                        lr=lr, wd=wd, momentum=self.momentum)
        else:
            self._fused("mp_sgd_update", weight, grad, (w32,),
                        lr=lr, wd=wd)


@register
class Signum(Optimizer):
    """Sign-of-momentum updates (reference: optimizer.py Signum:920)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype)) \
            if self.momentum else None

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is not None:
            self._fused("signum_update", weight, grad, (state,), lr=lr,
                        wd=wd, momentum=self.momentum, wd_lh=self.wd_lh)
        else:
            self._fused("signsgd_update", weight, grad, lr=lr, wd=wd)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    """Follow the moving leader (reference: optimizer.py FTML:830)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape)  # noqa: E731
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        t = self._bump(index)
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_grad"] = self.clip_gradient   # ftml's knob name
        d, v, z = state
        nd.ftml_update(weight, grad, d, v, z, out=[weight, d, v, z],
                       lr=self._get_lr(index), wd=self._get_wd(index),
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t, **kw)


@register
class LBSGD(Optimizer):
    """Large-batch SGD: warmup multiplier schedules or LARS layer-wise
    trust ratios on top of momentum SGD
    (reference: optimizer.py LBSGD:678)."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype)) \
            if self.momentum else None

    def _warmup_mult(self, nup):
        """Ramp 1 -> batch_scale over the warmup window."""
        span = self.warmup_epochs * self.updates_per_epoch
        top = float(self.batch_scale)
        if nup >= span:
            return top
        if span <= 1:
            return 1.0
        frac = {"linear": nup / span,
                "power2": (nup / span) ** 2,
                "sqrt": math.sqrt(nup / span)}.get(self.warmup_strategy)
        return 1.0 + (top - 1.0) * frac if frac is not None else 1.0

    def _lars_ratio(self, weight, g, wd):
        """Trust ratio ||w|| / (||g|| + wd ||w||) per layer."""
        w2 = float((weight * weight).sum().asscalar())
        g2 = float((g * g).sum().asscalar())
        if not w2 or not g2:
            return 1.0
        return math.sqrt(w2 / (g2 + wd * w2 + 1e-18))

    def update(self, index, weight, grad, state):
        self._bump(index)
        wd = self._get_wd(index)
        if self.warmup_strategy == "lars":
            mult = self._lars_ratio(weight, grad, wd)
        else:
            mult = self._warmup_mult(self.num_update + self.init_updates)
        lr = self._get_lr(index) * mult
        if state is not None:
            self._fused("sgd_mom_update", weight, grad, (state,),
                        lr=lr, wd=wd, momentum=self.momentum)
        else:
            self._fused("sgd_update", weight, grad, lr=lr, wd=wd)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD:868):
    compensates stale gradients with a grad^2-scaled correction toward
    the weight drift since the gradient was computed."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        mom = nd.zeros(weight.shape, dtype=str(weight.dtype)) \
            if self.momentum else None
        return (mom, weight.copy())   # (momentum, weight snapshot)

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, snapshot = state
        drift = weight - snapshot
        g_comp = g + self.lamda * g * g * drift
        step = g_comp + wd * weight
        if mom is not None:
            m = self.momentum * mom - lr * step
            mom._data = m._data
            weight._data = (weight + m)._data
        else:
            weight._data = (weight - lr * step)._data
        snapshot._data = weight._data


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: optimizer.py NAG:938)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype)) \
            if self.momentum else None

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is not None:
            self._fused("nag_mom_update", weight, grad, (state,),
                        lr=lr, wd=wd, momentum=self.momentum)
        else:
            self._fused("sgd_update", weight, grad, lr=lr, wd=wd)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics: SGD plus N(0, lr) noise
    for posterior sampling (reference: optimizer.py SGLD:976)."""

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=str(weight.dtype))
        weight._data = (weight - lr / 2 * (g + wd * weight) + noise)._data


@register
class Adam(Optimizer):
    """Adam with in-lr bias correction (reference: optimizer.py
    Adam:1003 folds the correction into lr, not the moments)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, dtype=str(weight.dtype))  # noqa
        return (z(), z())

    def update(self, index, weight, grad, state):
        t = self._bump(index)
        lr = self._get_lr(index) * \
            math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        self._fused("adam_update", weight, grad, (mean, var), lr=lr,
                    wd=self._get_wd(index), beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon)


@register
class AdaGrad(Optimizer):
    """AdaGrad with a row-sparse fast path (reference: optimizer.py
    AdaGrad:1140 over _sparse_adagrad_update)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.RowSparseNDArray):
            _sp.adagrad_row_update(weight, grad, state, lr=lr, wd=wd,
                                   epsilon=self.float_stable_eps,
                                   **self._common_knobs())
            return
        self._fused("_sparse_adagrad_update", weight,
                    self._densify(grad), (state,), lr=lr, wd=wd,
                    epsilon=self.float_stable_eps)


@register
class RMSProp(Optimizer):
    """RMSProp, plain or centered (reference: optimizer.py
    RMSProp:1063; Tieleman & Hinton / Graves variants)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered, self.epsilon = centered, epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape)  # noqa: E731
        return (z(), z(), z()) if self.centered else z()

    def update(self, index, weight, grad, state):
        self._bump(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        extra = {"clip_weights": self.clip_weights} \
            if self.clip_weights else {}
        if self.centered:
            n, g, delta = state
            self._fused("rmspropalex_update", weight, grad,
                        (n, g, delta), lr=lr, wd=wd, gamma1=self.gamma1,
                        gamma2=self.gamma2, epsilon=self.epsilon,
                        **extra)
        else:
            self._fused("rmsprop_update", weight, grad, (state,),
                        lr=lr, wd=wd, gamma1=self.gamma1,
                        epsilon=self.epsilon, **extra)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: optimizer.py AdaDelta:1224; lr-free)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        self._bump(index)
        acc_g, acc_delta = state
        self._fused("adadelta_update", weight, grad, (acc_g, acc_delta),
                    rho=self.rho, epsilon=self.epsilon,
                    wd=self._get_wd(index))


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference: optimizer.py Ftrl:1160)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))  # z, n

    def update(self, index, weight, grad, state):
        self._bump(index)
        z, n = state
        self._fused("ftrl_update", weight, grad, (z, n),
                    lr=self._get_lr(index), wd=self._get_wd(index),
                    lamda1=self.lamda1, beta=self.beta)


@register
class Adamax(Optimizer):
    """Adamax — Adam under the infinity norm (reference: optimizer.py
    Adamax:1264)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        t = self._bump(index)
        mean, var = state
        self._fused("adamax_update", weight, grad, (mean, var),
                    lr=self._get_lr(index), wd=self._get_wd(index),
                    beta1=self.beta1, beta2=self.beta2, t=t)


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: optimizer.py Nadam:1319)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        t = self._bump(index)
        mean, var = state
        self._fused("nadam_update", weight, grad, (mean, var),
                    lr=self._get_lr(index), wd=self._get_wd(index),
                    beta1=self.beta1, beta2=self.beta2,
                    epsilon=self.epsilon, t=t,
                    schedule_decay=self.schedule_decay)


@register
class Test(Optimizer):
    """Reference's test optimizer: w -= lr * grad (pure python path)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        weight._data = (weight - self.learning_rate *
                        (grad * self.rescale_grad))._data


# ---------------------------------------------------------------------------


class Updater:
    """Per-index state store applying an optimizer
    (reference: optimizer.py Updater:1504 — runs on kvstore servers too)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return ("nd", s.asnumpy())
            if isinstance(s, (tuple, list)):
                return ("tuple", [to_np(x) for x in s])
            return ("raw", s)
        payload = {k: to_np(v) for k, v in self.states.items()}
        # format 2: the payload travels with the writing optimizer's
        # identity (class + baked hyper-param signature) so a resumed
        # job can detect stale/foreign state instead of silently
        # applying it — see states_mismatch().  The marker key cannot
        # collide with the legacy payload's int indices.
        blob = {"__format__": 2, "states": payload,
                "opt_class": type(self.optimizer).__name__,
                "hyper_sig": _hyper_sig_list(self.optimizer)}
        if dump_optimizer:
            blob["optimizer"] = self.optimizer
        return pickle.dumps(blob)

    def set_states(self, states):
        # accepts the raw bytes, or an already-unpickled blob — a
        # validated load (states_mismatch) must not deserialize the
        # full momenta payload twice
        data = pickle.loads(states) \
            if isinstance(states, (bytes, bytearray, memoryview)) \
            else states
        if isinstance(data, dict) and data.get("__format__") == 2:
            payload = data["states"]
            if "optimizer" in data:
                self.optimizer = data["optimizer"]
        elif isinstance(data, tuple):        # legacy (payload, optimizer)
            payload, self.optimizer = data
        else:                                 # legacy bare payload
            payload = data

        def from_np(s):
            kind, v = s
            if kind == "nd":
                return nd.array(v)
            if kind == "tuple":
                return tuple(from_np(x) for x in v)
            return v
        self.states = {k: from_np(v) for k, v in payload.items()}
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer):
    return Updater(optimizer)


def _hyper_sig_list(optimizer):
    """tree_opt.hyper_sig as a list (late import: tree_opt pulls
    jax.numpy, this module must stay importable in jax-light
    processes like kvstore servers mid-bootstrap)."""
    from .tree_opt import hyper_sig
    return list(hyper_sig(optimizer))


def states_mismatch(blob, optimizer):
    """'' when *blob* (``Updater.get_states`` bytes, or the
    already-unpickled object) belongs to *optimizer*; otherwise a
    human-readable reason.

    Format-2 blobs carry the writing optimizer's class name and baked
    hyper-param signature (``tree_opt._HYPER_ATTRS``: rescale_grad,
    momentum, betas, ...).  Restoring momentum buffers into an Adam,
    or state written under a different rescale_grad, silently trains
    wrong after a resume — the caller turns a non-empty reason into a
    typed :class:`~mxnet_tpu.resilience.StateMismatchError` (or
    warn-and-reinit under ``MXNET_OPTSTATE_MISMATCH=reinit``).
    Legacy header-less blobs validate vacuously ('' — nothing to
    check against)."""
    try:
        data = pickle.loads(blob) \
            if isinstance(blob, (bytes, bytearray, memoryview)) \
            else blob
    except Exception as exc:
        return "unreadable optimizer-state blob (%s: %s)" % (
            type(exc).__name__, exc)
    if not (isinstance(data, dict) and data.get("__format__") == 2):
        return ""
    want_cls = type(optimizer).__name__
    got_cls = data.get("opt_class")
    if got_cls != want_cls:
        return ("blob was written by optimizer class %r, current "
                "optimizer is %r" % (got_cls, want_cls))
    cur = _hyper_sig_list(optimizer)
    saved = data.get("hyper_sig")
    if saved is not None and list(saved) != cur:
        from .tree_opt import _HYPER_ATTRS
        diffs = [a for a, s, c in zip(_HYPER_ATTRS, saved, cur)
                 if s != c]
        return ("hyper-param signature changed since the blob was "
                "written: %s" % ", ".join(diffs or ["<layout>"]))
    return ""
