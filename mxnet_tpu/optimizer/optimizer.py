"""Optimizers (reference: python/mxnet/optimizer/optimizer.py, 1,571 LoC).

Each ``update`` dispatches to the fused update ops in
``mxnet_tpu/ops/optimizer_ops.py`` (one XLA computation per update, weight
buffers donated), mirroring the reference's fused optimizer kernels
(src/operator/optimizer_op.cc:43-651).  ``Updater`` reproduces the
serializable per-index state store that KVStore servers run
(optimizer.py:1504).
"""

from __future__ import annotations

import math
import pickle

import numpy as _np

from ..base import registry as _registry
from .. import ndarray as nd
from ..ndarray import NDArray

_reg = _registry("optimizer")

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "FTML", "LBSGD",
           "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test", "Updater",
           "create", "register", "get_updater"]


def register(klass):
    _reg.register(klass)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _reg.get(name)(**kwargs)


class Optimizer:
    """Base optimizer (reference: optimizer.py Optimizer:46)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- lr/wd resolution --------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already "
                              "been defined.")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # _gamma (BatchNorm scale) keeps weight decay, like _weight
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (_np.float16,
                                                     "bfloat16") or \
                (self.multi_precision and
                 str(weight.dtype) in ("float16", "bfloat16")):
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) and \
                isinstance(state[-1], NDArray) and \
                state[-1].dtype == _np.float32 and \
                weight.dtype != _np.float32:
            self._update_mp(index, weight, grad, state)
        else:
            self.update(index, weight, grad, state)

    def _update_mp(self, index, weight, grad, state):
        # generic mp fallback: update the fp32 master then cast down
        inner_state, w32 = state
        g32 = grad.astype("float32")
        self.update(index, w32, g32, inner_state)
        weight._data = w32._data.astype(weight._data.dtype)

    def _common_kwargs(self, index):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


# ---------------------------------------------------------------------------


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision
    (reference: optimizer.py SGD:451)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=str(weight.dtype))
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (_np.float16,) or \
                str(weight.dtype) == "bfloat16" and self.multi_precision:
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.RowSparseNDArray) and self.lazy_update:
            # lazy row-wise path: only the gradient's rows are touched
            # (reference: optimizer_op.cc sgd row_sparse lazy_update)
            if state is not None:
                _sp.sgd_mom_row_update(weight, grad, state, lr=lr,
                                       momentum=self.momentum, wd=wd,
                                       **kw)
            else:
                _sp.sgd_row_update(weight, grad, lr=lr, wd=wd, **kw)
            return
        if isinstance(grad, _sp.BaseSparseNDArray):
            grad = grad.todense()
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if isinstance(state, tuple) and isinstance(state[1], NDArray) and \
                state[1].dtype == _np.float32 and \
                weight.dtype != _np.float32:
            from ..ndarray import sparse as _sp
            if isinstance(grad, _sp.BaseSparseNDArray):
                # the fused mp kernels are dense-only; correctness over
                # laziness for the fp32-master path
                grad = grad.todense()
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            kw = self._common_kwargs(index)
            mom, w32 = state
            if mom is not None:
                nd.mp_sgd_mom_update(weight, grad, mom, w32,
                                     out=[weight, mom, w32], lr=lr, wd=wd,
                                     momentum=self.momentum, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, out=[weight, w32],
                                 lr=lr, wd=wd, **kw)
        else:
            self.update(index, weight, grad, state)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=str(weight.dtype))
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if state is not None:
            nd.signum_update(weight, grad, state, out=[weight, state],
                             lr=lr, wd=wd, momentum=self.momentum,
                             wd_lh=self.wd_lh, **kw)
        else:
            nd.signsgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape),
                nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_grad"] = self.clip_gradient
        nd.ftml_update(weight, grad, d, v, z, out=[weight, d, v, z],
                       lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t, **kw)


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS layer-wise adaptation
    (reference: optimizer.py LBSGD:678)."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=str(weight.dtype))
        return None

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        strategy = self.warmup_strategy
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            mult = maxmult
        elif nwup <= 1:
            mult = 1.0
        else:
            if strategy == "linear":
                mult = 1.0 + (maxmult - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
            else:
                mult = 1.0
        return mult

    def _get_lars(self, weight, g, wd):
        """LARS trust ratio ||w|| / (||g|| + wd*||w||)."""
        w2 = float((weight * weight).sum().asscalar())
        g2 = float((g * g).sum().asscalar())
        if w2 == 0 or g2 == 0:
            return 1.0
        return math.sqrt(w2 / (g2 + wd * w2 + 1e-18))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        if self.warmup_strategy == "lars":
            lbmult = self._get_lars(weight, grad, wd)
        else:
            lbmult = self._get_lbmult(self.num_update + self.init_updates)
        lr = self._get_lr(index) * lbmult
        kw = self._common_kwargs(index)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD:868)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, dtype=str(weight.dtype)),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        comp = grad + self.lamda * grad * grad * (weight - previous_weight)
        if mom is not None:
            m = self.momentum * mom - lr * (comp + wd * weight)
            mom._data = m._data
            weight._data = (weight + m)._data
        else:
            weight._data = (weight - lr * (comp + wd * weight))._data
        previous_weight._data = weight._data


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=str(weight.dtype))
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if state is not None:
            nd.nag_mom_update(weight, grad, state, out=[weight, state],
                              lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, out=weight, lr=lr, wd=wd, **kw)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics
    (reference: optimizer.py SGLD:976)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=str(weight.dtype))
        weight._data = (weight - lr / 2 * (grad + wd * weight) +
                        noise)._data


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=str(weight.dtype)),
                nd.zeros(weight.shape, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        kw = self._common_kwargs(index)
        nd.adam_update(weight, grad, mean, var, out=[weight, mean, var],
                       lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, **kw)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        from ..ndarray import sparse as _sp
        if isinstance(grad, _sp.RowSparseNDArray):
            _sp.adagrad_row_update(weight, grad, state, lr=lr, wd=wd,
                                   epsilon=self.float_stable_eps, **kw)
            return
        if isinstance(grad, _sp.BaseSparseNDArray):
            grad = grad.todense()
        nd._sparse_adagrad_update(weight, grad, state, out=[weight, state],
                                  lr=lr, wd=wd,
                                  epsilon=self.float_stable_eps, **kw)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape), nd.zeros(weight.shape),
                    nd.zeros(weight.shape))
        return nd.zeros(weight.shape)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs(index)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  out=[weight, n, g, delta], lr=lr, wd=wd,
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, **kw)
        else:
            nd.rmsprop_update(weight, grad, state, out=[weight, state],
                              lr=lr, wd=wd, gamma1=self.gamma1,
                              epsilon=self.epsilon, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        kw = self._common_kwargs(index)
        nd.adadelta_update(weight, grad, acc_g, acc_delta,
                           out=[weight, acc_g, acc_delta], rho=self.rho,
                           epsilon=self.epsilon, wd=wd, **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        kw = self._common_kwargs(index)
        nd.ftrl_update(weight, grad, z, n, out=[weight, z, n], lr=lr,
                       wd=wd, lamda1=self.lamda1, beta=self.beta, **kw)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = self._common_kwargs(index)
        nd.adamax_update(weight, grad, mean, var, out=[weight, mean, var],
                         lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                         t=t, **kw)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape), nd.zeros(weight.shape))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = self._common_kwargs(index)
        nd.nadam_update(weight, grad, mean, var, out=[weight, mean, var],
                        lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, t=t,
                        schedule_decay=self.schedule_decay, **kw)


@register
class Test(Optimizer):
    """Reference's test optimizer: w -= lr * grad (pure python path)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        weight._data = (weight - self.learning_rate *
                        (grad * self.rescale_grad))._data


# ---------------------------------------------------------------------------


class Updater:
    """Per-index state store applying an optimizer
    (reference: optimizer.py Updater:1504 — runs on kvstore servers too)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return ("nd", s.asnumpy())
            if isinstance(s, (tuple, list)):
                return ("tuple", [to_np(x) for x in s])
            return ("raw", s)
        payload = {k: to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((payload, self.optimizer))
        return pickle.dumps(payload)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple):
            payload, self.optimizer = data
        else:
            payload = data

        def from_np(s):
            kind, v = s
            if kind == "nd":
                return nd.array(v)
            if kind == "tuple":
                return tuple(from_np(x) for x in v)
            return v
        self.states = {k: from_np(v) for k, v in payload.items()}
        self.states_synced = {k: False for k in self.states}


def get_updater(optimizer):
    return Updater(optimizer)
