"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py, 423 LoC:
Trainer:27, _init_kvstore:158, step:254, allreduce_grads:282, update:314).

TPU-native: with a single device (or one logical sharded copy) the trainer
applies fused update ops directly; with multiple per-context replicas it
reduces gradients across contexts (the reference's kvstore='device' path);
with ``kvstore='tpu'`` gradient reduction happens in-graph over the mesh
(see mxnet_tpu/kvstore.py) and the trainer only runs the update.
"""

from __future__ import annotations

from .. import optimizer as opt
from ..ndarray import NDArray
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
            self._param2idx[param.name] = i
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._params_to_init = list(self._params)

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data is not None or \
                param._deferred_init is not None else None
            if ctx is None:
                continue
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of " \
                "contexts, but Parameter %s is initialized on %s while " \
                "previous Parameters are initialized on %s." % (
                    param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer,
                                         param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts or [None]]

    def _init_kvstore(self):
        kv_type = self._kvstore_type
        if isinstance(kv_type, str) and "dist" in kv_type:
            from .. import kvstore as kvs
            self._kvstore = kvs.create(kv_type)
            # distributed: weights live on the server; optimizer runs
            # server-side (reference: trainer.py _init_kvstore:158 with
            # update_on_kvstore)
            if self._update_on_kvstore is None:
                self._update_on_kvstore = True
            for i, param in enumerate(self._params):
                if param._data is None:
                    continue
                self._kvstore.init(i, param.data())
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
        elif kv_type and len(self._contexts) > 1 and \
                kv_type not in ("device", "local"):
            from .. import kvstore as kvs
            self._kvstore = kvs.create(kv_type)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def _row_sparse_pull(self, parameter, out, row_id,
                         full_idx=False):
        # single-copy path: weights are already local
        pass

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update
        (reference: trainer.py step:254)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad)

    def allreduce_grads(self):
        """Sum gradients across per-context replicas
        (reference: _allreduce_grads:282 over kvstore push/pull)."""
        for param in self._params:
            if param.grad_req == "null" or param._data is None:
                continue
            grads = param.list_grad()
            if len(grads) <= 1:
                continue
            total = grads[0]
            for g in grads[1:]:
                total = total + g.as_in_context(total.context)
            for g in grads:
                total.as_in_context(g.context).copyto(g)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        from ..ndarray import sparse as _sp

        # Only optimizers with a lazy row-wise kernel may see row-sparse
        # grads locally (others' fused dense ops would misbroadcast the
        # (nnz, dim) values array); the dist wire is always safe — the
        # server reconstructs dense before its updater runs.
        _lazy_ok = isinstance(self._optimizer, (opt.SGD, opt.AdaGrad)) \
            and not getattr(self._optimizer, "multi_precision", False)

        def _maybe_sparse(param, grad, for_wire):
            # Embedding(sparse_grad=True)-style params: the tape computes
            # the gradient dense (XLA scatter-add); compress to
            # row_sparse at the framework boundary so the kvstore wire
            # and the optimizer's lazy row update see only touched rows.
            if param._grad_stype == "row_sparse" and \
                    (for_wire or _lazy_ok) and \
                    not isinstance(grad, _sp.BaseSparseNDArray):
                return _sp.compress_rowsparse(grad)
            return grad

        if self._kvstore is not None and self._update_on_kvstore:
            # distributed: push grads, pull updated weights (reference:
            # trainer.py _update with update_on_kvstore)
            for i, param in enumerate(self._params):
                if param.grad_req == "null" or param._data is None:
                    continue
                self._kvstore.push(i, [_maybe_sparse(param, g, True)
                                       for g in param.list_grad()])
            self._kvstore.barrier()
            for i, param in enumerate(self._params):
                if param.grad_req == "null" or param._data is None:
                    continue
                self._kvstore.pull(i, out=param.list_data())
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, _maybe_sparse(param, grad, False), arr)
            # re-mark so subsequent autograd passes see updated weights
            if param._grad is not None:
                from .. import autograd
                for c, d in param._data.items():
                    autograd.mark_variables([d], [param._grad[c]],
                                            param._grad_req)

    def save_states(self, fname):
        assert self._optimizer is not None
        from ..resilience.checkpoint import atomic_write
        atomic_write(fname,
                     self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            states = f.read()
        for updater in self._updaters:
            updater.set_states(states)
            updater.optimizer = self._optimizer
