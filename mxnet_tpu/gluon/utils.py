"""Gluon utilities (reference: python/mxnet/gluon/utils.py: split_data,
split_and_load, clip_global_norm, check_sha1, download)."""

from __future__ import annotations

import math

import numpy as _np

from .. import ndarray as nd
from ..ndarray import NDArray
from ..context import Context

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks
    (reference: utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." %
            (str(data.shape), num_slice, batch_axis, num_slice))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and place slices on each context
    (reference: utils.py split_and_load)."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the global 2-norm <= max_norm
    (reference: utils.py clip_global_norm)."""
    assert len(arrays) > 0
    total = 0.0
    for arr in arrays:
        total += float((arr * arr).sum().asscalar())
    total_norm = math.sqrt(total)
    if check_isfinite and not _np.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm
