"""Gluon losses.

API parity target: the reference's ``python/mxnet/gluon/loss.py`` (769
LoC) — same class names, constructor knobs, and reduction semantics
(elementwise loss -> optional ``sample_weight``/``weight`` scaling ->
mean over every axis except ``batch_axis``).  The plumbing lives once in
``Loss._per_sample`` here instead of being repeated per class; each
subclass contributes only its formula via ``_elementwise`` (or a full
``hybrid_forward`` where the shape story differs, e.g. pick-based CE,
CTC, triplet).
"""

from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss"]


class Loss(HybridBlock):
    """Base class: holds the global ``weight`` scale and the batch axis
    the reduction preserves."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{}(batch_axis={}, w={})".format(
            type(self).__name__, self._batch_axis, self._weight)

    # -- shared reduction plumbing ------------------------------------
    def _scale(self, F, loss, sample_weight):
        """Per-element ``sample_weight`` (broadcast), then the scalar
        ``weight`` knob."""
        if sample_weight is not None:
            loss = F.broadcast_mul(loss, sample_weight)
        if self._weight is not None:
            loss = loss * self._weight
        return loss

    def _per_sample(self, F, loss, sample_weight):
        """Scale, then collapse everything but the batch axis."""
        loss = self._scale(F, loss, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)

    def _elementwise(self, F, pred, label):
        raise NotImplementedError

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        # default pattern: label takes pred's shape, formula, reduce
        raw = self._elementwise(F, pred, F.reshape_like(label, pred))
        return self._per_sample(F, raw, sample_weight)


class L2Loss(Loss):
    """Half squared error (the 1/2 lives in the formula, so the weight
    knob composes with it)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _elementwise(self, F, pred, label):
        return 0.5 * F.square(pred - label)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _elementwise(self, F, pred, label):
        return F.abs(pred - label)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE on logits (default) or on probabilities
    (``from_sigmoid=True``)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = F.reshape_like(label, pred)
        if self._from_sigmoid:
            # clamp away from log(0)
            eps = 1e-12
            hit = F.log(pred + eps) * label
            if pos_weight is not None:
                hit = F.broadcast_mul(hit, pos_weight)
            miss = F.log(1. - pred + eps) * (1. - label)
            raw = -(hit + miss)
        elif pos_weight is None:
            # logit form, the overflow-safe identity:
            #   bce(x, z) = max(x, 0) - x*z + log1p(exp(-|x|))
            softplus_neg_abs = F.Activation(-F.abs(pred),
                                            act_type="softrelu")
            raw = F.relu(pred) - pred * label + softplus_neg_abs
        else:
            # positive-class weighting: the log1p term picks up the
            # weight  1 + (pos_weight - 1) * z  (derivation: weighted
            # -[w*z*log(s(x)) + (1-z)*log(1-s(x))] regrouped around the
            # same stable softplus)
            lw = 1. + F.broadcast_mul(pos_weight - 1., label)
            softplus = F.Activation(-F.abs(pred), act_type="softrelu") + \
                F.relu(-pred)
            raw = pred - pred * label + F.broadcast_mul(lw, softplus)
        return self._per_sample(F, raw, sample_weight)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Cross entropy over ``axis``; integer labels gather via pick
    (``sparse_label=True``), dense labels contract against the full
    log-probability row."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits else \
            F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            raw = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            raw = -F.sum(logp * F.reshape_like(label, logp),
                         axis=self._axis, keepdims=True)
        return self._per_sample(F, raw, sample_weight)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """KL(label || pred) with pred given as log-probabilities by
    default; the label-entropy term keeps the minimum at zero."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits else \
            F.log_softmax(pred, axis=self._axis)
        raw = label * (F.log(label + 1e-12) - logp)
        return self._per_sample(F, raw, sample_weight)


class CTCLoss(Loss):
    """Layout-normalizing wrapper over the CTCLoss op (blank = last
    class, as in the reference's warp-ctc binding)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        super().__init__(weight, label_layout.find("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":  # op wants time-major activations
            pred = F.swapaxes(pred, 0, 1) if hasattr(F, "swapaxes") else \
                F.SwapAxis(pred, dim1=0, dim2=1)
        if self._batch_axis == 1:
            label = F.SwapAxis(label, dim1=0, dim2=1)
        # the length tensors are optional op INPUTS gated by the flags
        extra = [t for t in (pred_lengths, label_lengths) if t is not None]
        raw = F.CTCLoss(pred, label, *extra,
                        use_data_lengths=pred_lengths is not None,
                        use_label_lengths=label_lengths is not None,
                        blank_label="last")
        return self._scale(F, raw, sample_weight)


class HuberLoss(Loss):
    """Quadratic within ``rho`` of the target, linear beyond."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def _elementwise(self, F, pred, label):
        err = F.abs(pred - label)
        quad = (0.5 / self._rho) * F.square(err)
        lin = err - 0.5 * self._rho
        return F.where(err > self._rho, lin, quad)


class HingeLoss(Loss):
    """max(0, margin - y*f(x)) for signed labels."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def _elementwise(self, F, pred, label):
        return F.relu(self._margin - pred * label)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def _elementwise(self, F, pred, label):
        return F.square(F.relu(self._margin - pred * label))


class LogisticLoss(Loss):
    """BCE on logits with labels in {-1, 1} (``signed``, remapped to
    {0, 1}) or already in {0, 1} (``binary``)."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def _elementwise(self, F, pred, label):
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        softplus_neg_abs = F.Activation(-F.abs(pred), act_type="softrelu")
        return F.relu(pred) - pred * label + softplus_neg_abs


class TripletLoss(Loss):
    """max(0, margin + ||a-p||^2 - ||a-n||^2), distances summed over
    the non-batch axes before the hinge."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        d_pos = F.square(F.reshape_like(positive, pred) - pred)
        d_neg = F.square(F.reshape_like(negative, pred) - pred)
        gap = F.sum(d_pos - d_neg, axis=self._batch_axis, exclude=True)
        return self._scale(F, F.relu(gap + self._margin), sample_weight)
