"""Gluon recurrent layers and cells (reference capability:
python/mxnet/gluon/rnn/)."""

from .rnn_layer import RNN, LSTM, GRU  # noqa: F401
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell,  # noqa
                       LSTMCell, GRUCell, SequentialRNNCell, DropoutCell,
                       BidirectionalCell, ResidualCell, ModifierCell)

__all__ = ["RNN", "LSTM", "GRU", "RecurrentCell", "HybridRecurrentCell",
           "RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "DropoutCell", "BidirectionalCell", "ResidualCell",
           "ModifierCell"]
