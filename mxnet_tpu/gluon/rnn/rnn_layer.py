"""Fused recurrent layers over the RNN op.

Reference capability: python/mxnet/gluon/rnn/rnn_layer.py (RNN/LSTM/GRU
wrapping the fused cuDNN RNN op).  Here the fused op is a `lax.scan`
program (ops/rnn.py); each layer owns per-(layer, direction) parameters
named like the reference ({l|r}{i}_{i2h|h2h}_{weight|bias}) and packs
them into the op's flat vector at forward time — the pack is pure
reshapes/concat, which XLA folds away.
"""

from __future__ import annotations

from ..block import HybridBlock
from ... import ndarray as nd
from ...base import MXNetError

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise ValueError("layout must be TNC or NTC, got %r" % layout)
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._dtype = dtype
        self._gates = _GATES[mode]
        ng, nh = self._gates, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for d in ("l", "r")[:self._dir]:
                    in_sz = input_size if i == 0 else hidden_size * self._dir
                    for conn, wshape, bshape in (
                            ("i2h", (ng * nh, in_sz), (ng * nh,)),
                            ("h2h", (ng * nh, nh), (ng * nh,))):
                        wname = "%s%d_%s_weight" % (d, i, conn)
                        bname = "%s%d_%s_bias" % (d, i, conn)
                        winit = i2h_weight_initializer if conn == "i2h" \
                            else h2h_weight_initializer
                        binit = i2h_bias_initializer if conn == "i2h" \
                            else h2h_bias_initializer
                        setattr(self, wname, self.params.get(
                            wname, shape=wshape, init=winit, dtype=dtype,
                            allow_deferred_init=True))
                        setattr(self, bname, self.params.get(
                            bname, shape=bshape, init=binit, dtype=dtype))

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size,
                 self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape, "__layout__": "LNC"},
                    {"shape": shape, "__layout__": "LNC"}]
        return [{"shape": shape, "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        func = func or nd.zeros
        if kwargs.get("ctx") is None:
            kwargs.pop("ctx", None)
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def _finish_deferred(self, x):
        """Resolve layer-0 input size from the first real input (the
        reference's infer-shape does this inside the C++ op)."""
        if self._input_size:
            return
        axis = 2 if self._layout == "TNC" else 2  # feature dim is last
        in_sz = x.shape[axis]
        self._input_size = in_sz
        ng, nh = self._gates, self._hidden_size
        for d in ("l", "r")[:self._dir]:
            p = getattr(self, "%s0_i2h_weight" % d)
            p.shape = (ng * nh, in_sz)
        for p in self.collect_params().values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def __call__(self, inputs, states=None, **kwargs):
        if isinstance(inputs, nd.NDArray):
            self._finish_deferred(inputs)
        if states is None:
            # stateless call: the fused op starts from zeros in-graph, so
            # this path works both eagerly and under symbolic tracing
            return super().__call__(inputs)
        if isinstance(states, nd.NDArray) or not isinstance(
                states, (list, tuple)):
            states = [states]
        out = super().__call__(inputs, *states)
        sep = out if isinstance(out, (list, tuple)) else [out]
        return sep[0], list(sep[1:])

    def hybrid_forward(self, F, inputs, *states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        parts = []
        for conn in ("weight", "bias"):
            for i in range(self._num_layers):
                for d in ("l", "r")[:self._dir]:
                    for loc in ("i2h", "h2h"):
                        p = params["%s%d_%s_%s" % (d, i, loc, conn)]
                        parts.append(F.reshape(p, shape=(-1,)))
        flat = F.concat(*parts, dim=0) if len(parts) > 1 else parts[0]
        rnn_out = F.RNN(inputs, flat, *states,
                        state_size=self._hidden_size,
                        num_layers=self._num_layers,
                        bidirectional=self._dir == 2,
                        p=self._dropout, state_outputs=bool(states),
                        mode=self._mode)
        if not states:
            outputs = rnn_out
            states_out = []
        else:
            outputs = rnn_out[0]
            states_out = list(rnn_out[1:])
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if not states_out:
            return outputs
        return [outputs] + states_out

    def __repr__(self):
        return "%s(%s, %d, layers=%d%s)" % (
            type(self).__name__, self._mode, self._hidden_size,
            self._num_layers, ", bidirectional" if self._dir == 2 else "")


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh) layer."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        super().__init__("rnn_" + activation, hidden_size, num_layers,
                         layout, dropout, bidirectional,
                         input_size=input_size, **kwargs)


class LSTM(_RNNLayer):
    """Multi-layer LSTM layer (gate order i,f,g,o)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size=input_size, **kwargs)


class GRU(_RNNLayer):
    """Multi-layer GRU layer (gate order r,z,n; linear-before-reset)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size=input_size, **kwargs)
