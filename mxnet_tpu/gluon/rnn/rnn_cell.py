"""Recurrent cells — single-step recurrence as HybridBlocks.

Reference capability: python/mxnet/gluon/rnn/rnn_cell.py (RNNCell,
LSTMCell, GRUCell, SequentialRNNCell, modifier cells, unroll).  Cells
exist for custom recurrences and attention-style loops; the fused layers
(rnn_layer.py) are the fast path.  ``unroll`` traces the step function
per timestep — under hybridize the unrolled chain is one XLA program.
"""

from __future__ import annotations

from ..block import HybridBlock
from ... import ndarray as nd


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(HybridBlock):
    """Base: a cell maps (input_t, states) -> (output_t, new_states)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells, call the modifier's begin_state"
        func = func or nd.zeros
        if kwargs.get("ctx") is None:
            kwargs.pop("ctx", None)
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if isinstance(states, nd.NDArray):
            states = [states]
        return super().__call__(inputs, *states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell *length* steps.

        inputs: (B, T, C) for NTC / (T, B, C) for TNC, or a list of T
        (B, C) arrays.  Returns (outputs, final_states) with outputs
        merged to one array when merge_outputs is not False.
        """
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            assert len(inputs) == length
            steps = list(inputs)
            batch = steps[0].shape[0]
        else:
            batch = inputs.shape[layout.find("N")]
            steps = nd.split(inputs, num_outputs=length, axis=axis,
                             squeeze_axis=True)
            if isinstance(steps, nd.NDArray):
                steps = [steps]
            else:
                steps = list(steps)
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd.SequenceMask(
                stacked, valid_length, use_sequence_length=True,
                axis=axis)
            if merge_outputs is False:
                outputs = [o for o in nd.split(
                    stacked, num_outputs=length, axis=axis,
                    squeeze_axis=True)]
            else:
                return stacked, states
        if merge_outputs is None or merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states


class HybridRecurrentCell(RecurrentCell):
    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _BaseGatedCell(HybridRecurrentCell):
    """Shared parameter plumbing for the three dense-gate cells."""

    def __init__(self, hidden_size, gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        g = gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(g * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(g * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(g * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(g * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def _proj(self, F, x, h, i2h_weight, h2h_weight, i2h_bias, h2h_bias,
              gates):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size * gates)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size * gates)
        return i2h, h2h


class RNNCell(_BaseGatedCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._proj(F, inputs, states, i2h_weight, h2h_weight,
                              i2h_bias, h2h_bias, 1)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(_BaseGatedCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._proj(F, inputs, h, i2h_weight, h2h_weight,
                              i2h_bias, h2h_bias, 4)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(slices[0], act_type="sigmoid")
        f = F.Activation(slices[1], act_type="sigmoid")
        g = F.Activation(slices[2], act_type="tanh")
        o = F.Activation(slices[3], act_type="sigmoid")
        nc = f * c + i * g
        nh = o * F.Activation(nc, act_type="tanh")
        return nh, [nh, nc]


class GRUCell(_BaseGatedCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, h, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._proj(F, inputs, h, i2h_weight, h2h_weight,
                              i2h_bias, h2h_bias, 3)
        xi = F.SliceChannel(i2h, num_outputs=3, axis=1)
        hi = F.SliceChannel(h2h, num_outputs=3, axis=1)
        r = F.Activation(xi[0] + hi[0], act_type="sigmoid")
        z = F.Activation(xi[1] + hi[1], act_type="sigmoid")
        n = F.Activation(xi[2] + r * hi[2], act_type="tanh")
        nh = (1 - z) * n + z * h
        return nh, [nh]


class SequentialRNNCell(RecurrentCell):
    """Stack cells; states are concatenated across children."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cells = []

    def add(self, cell):
        self.register_child(cell)
        self._cells.append(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._cells, batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._cells, batch_size=batch_size,
                                  **kwargs)

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if isinstance(states, nd.NDArray):
            states = [states]
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[pos:pos + n])
            pos += n
            next_states.extend(st)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        return RecurrentCell.unroll(
            self, length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)

    def __len__(self):
        return len(self._cells)

    def __getitem__(self, i):
        return self._cells[i]


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference: ModifierCell)."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        base_cell._modified = True
        self.base_cell = base_cell
        self.register_child(base_cell)

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size,
                                           func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class DropoutCell(RecurrentCell):
    """Apply dropout on the input of every step."""

    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if self._rate > 0:
            inputs = nd.Dropout(inputs, p=self._rate)
        return inputs, states if isinstance(states, list) else [states]


class ResidualCell(ModifierCell):
    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(RecurrentCell):
    """Unroll-only cell running one cell forward and one backward."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self.register_child(l_cell)
        self.register_child(r_cell)

    def state_info(self, batch_size=0):
        return _cells_state_info([self._l_cell, self._r_cell], batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state([self._l_cell, self._r_cell],
                                  batch_size=batch_size, **kwargs)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "BidirectionalCell cannot be stepped — use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            steps = list(nd.split(inputs, num_outputs=length, axis=axis,
                                  squeeze_axis=True))
        else:
            steps = list(inputs)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch)
        n_l = len(self._l_cell.state_info())
        l_out, l_states = self._l_cell.unroll(
            length, steps, states[:n_l], layout="NTC",
            merge_outputs=False)
        r_out, r_states = self._r_cell.unroll(
            length, list(reversed(steps)), states[n_l:], layout="NTC",
            merge_outputs=False)
        outs = [nd.concat(lo, ro, dim=1)
                for lo, ro in zip(l_out, list(reversed(r_out)))]
        if merge_outputs is None or merge_outputs:
            outs = nd.stack(*outs, axis=axis)
        return outs, l_states + r_states
