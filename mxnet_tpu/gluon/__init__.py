"""Gluon — the imperative high-level API (reference: python/mxnet/gluon/)."""

from .parameter import Parameter, ParameterDict, Constant  # noqa: F401
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import data  # noqa: F401
from . import loss  # noqa: F401
from . import contrib  # noqa: F401
from .utils import split_data, split_and_load, clip_global_norm  # noqa
