"""Model zoo (reference capability: python/mxnet/gluon/model_zoo/)."""

from . import vision

__all__ = ["vision"]
