"""Decoder-only transformer language model — the model-zoo face of the
framework's long-context stack (SURVEY §5.7 TPU stance).

The reference zoo predates Transformers (its only transformer artifact
is `_contrib_div_sqrt_dim`, src/operator/contrib/transformer.cc:33); on
TPU the LM is a first-class headline model, so the zoo carries one.
Pre-norm GPT-style blocks over `gluon.contrib.nn.MultiHeadAttention`,
whose attention op lowers to the Pallas flash kernel on TPU (causal
block skipping, O(S·block) activation memory) and the chunked scan
elsewhere.  Everything hybridizes to one XLA program; under
`ParallelTrainer` the step runs dp/sp-sharded (ring attention via
`parallel.sequence` when the sequence axis is sharded).

Usage::

    net = get_transformer_lm(vocab=32000, dim=1024, heads=16, layers=12)
    logits = net(tokens)         # (B, S) int -> (B, S, vocab)
"""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn
from ..contrib.nn import MultiHeadAttention

__all__ = ["TransformerBlock", "TransformerLM", "get_transformer_lm",
           "tensor_parallel_specs"]


class TransformerBlock(HybridBlock):
    """One pre-norm block: x + MHA(LN(x)); x + MLP(LN(x))."""

    def __init__(self, dim, heads, mlp_ratio=4, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm()
            self.attn = MultiHeadAttention(dim, heads, causal=True,
                                           use_bias=False)
            self.ln2 = nn.LayerNorm()
            self.fc1 = nn.Dense(mlp_ratio * dim, activation="relu",
                                flatten=False)
            self.fc2 = nn.Dense(dim, flatten=False)

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        return x + self.fc2(self.fc1(self.ln2(x)))


class TransformerLM(HybridBlock):
    """Token embedding + learned positions + N blocks + LM head.

    ``max_seq`` bounds the learned positional table; inputs may be any
    length up to it (the table is slice_like-d to the sequence at
    trace time, so one set of weights serves every bucket length).
    """

    def __init__(self, vocab=32000, dim=512, heads=8, layers=6,
                 max_seq=8192, mlp_ratio=4, **kwargs):
        super().__init__(**kwargs)
        self._dim = dim
        with self.name_scope():
            self.embed = nn.Embedding(vocab, dim)
            self.pos = self.params.get(
                "pos_embed", shape=(1, max_seq, dim),
                init="normal")
            self.blocks = []
            for i in range(layers):
                blk = TransformerBlock(dim, heads, mlp_ratio,
                                       prefix="h%d_" % i)
                setattr(self, "h%d" % i, blk)
                self.blocks.append(blk)
            self.ln_f = nn.LayerNorm()
            self.head = nn.Dense(vocab, use_bias=False, flatten=False)

    def hybrid_forward(self, F, x, pos=None):
        h = self.embed(x)
        # (1, max_seq, D) -> (1, S, D), broadcast over batch
        p = F.slice_like(pos, h, axes=(1,))
        h = F.broadcast_add(h, p)
        for blk in self.blocks:
            h = blk(h)
        return self.head(self.ln_f(h))


def get_transformer_lm(vocab=32000, dim=512, heads=8, layers=6,
                       max_seq=8192, **kwargs):
    return TransformerLM(vocab=vocab, dim=dim, heads=heads,
                         layers=layers, max_seq=max_seq, **kwargs)


def tensor_parallel_specs(axis="tp"):
    """Megatron-style ``ParallelTrainer(param_specs=...)`` preset for
    :class:`TransformerLM`: attention q/k/v and the MLP up-projection
    are column-parallel (output dim sharded), the attention output and
    MLP down-projection row-parallel (input dim sharded) — each block
    then needs exactly one all-reduce per sublayer, which XLA inserts.
    Embedding and LM head stay replicated (their vocab dim rarely
    divides small tp extents; shard them via an explicit entry when it
    does)."""
    from jax.sharding import PartitionSpec as P
    col, row = P(axis, None), P(None, axis)
    return {
        r"(query|key|value)_weight$": col,
        r"out_weight$": row,
        r"fc1_weight$": col,
        r"fc2_weight$": row,
    }
