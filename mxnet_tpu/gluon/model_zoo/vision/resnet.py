"""ResNet v1/v2 (capability parity with the reference zoo's
resnet18-152; architecture from He et al. 2015 "Deep Residual Learning"
and 2016 "Identity Mappings").  The reference's four block classes
collapse into one `ResidualUnit` parameterized by (bottleneck, pre_act):
v1 is conv-BN-ReLU with post-addition activation, v2 is the pre-activation
variant.  Under hybridize/ParallelTrainer the whole network compiles to a
single XLA program; BN+ReLU chains fuse into the surrounding convs.
"""

from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "SpaceToDepthStem",
           "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]

# depth -> (bottleneck?, units per stage, stage output channels)
_SPECS = {
    18: (False, (2, 2, 2, 2), (64, 128, 256, 512)),
    34: (False, (3, 4, 6, 3), (64, 128, 256, 512)),
    50: (True, (3, 4, 6, 3), (256, 512, 1024, 2048)),
    101: (True, (3, 4, 23, 3), (256, 512, 1024, 2048)),
    152: (True, (3, 8, 36, 3), (256, 512, 1024, 2048)),
}
_STEM_CHANNELS = 64


def _conv(ch, k, s, p):
    return nn.Conv2D(ch, kernel_size=k, strides=s, padding=p,
                     use_bias=False)


class ResidualUnit(HybridBlock):
    """One residual unit.

    bottleneck: 1x1 -> 3x3 -> 1x1 (channels//4 inner width) vs two 3x3.
    pre_act (v2): BN-ReLU precedes the convs and the shortcut branches
    off the activated tensor; otherwise (v1) the classic conv-BN-ReLU
    order with ReLU after the addition.
    """

    def __init__(self, channels, stride, in_channels, bottleneck,
                 pre_act, **kwargs):
        super().__init__(**kwargs)
        self._pre_act = pre_act
        self._project = stride != 1 or in_channels != channels
        inner = channels // 4 if bottleneck else channels
        if bottleneck:
            # v1 strides the leading 1x1; v2 strides the 3x3 (matching
            # the two He et al. papers and the reference blocks)
            if pre_act:
                plan = [(inner, 1, 1, 0), (inner, 3, stride, 1),
                        (channels, 1, 1, 0)]
            else:
                plan = [(inner, 1, stride, 0), (inner, 3, 1, 1),
                        (channels, 1, 1, 0)]
        else:
            plan = [(channels, 3, stride, 1), (channels, 3, 1, 1)]
        with self.name_scope():
            self.convs = []
            self.bns = []
            for j, (ch, k, s, p) in enumerate(plan):
                conv = _conv(ch, k, s, p)
                bn = nn.BatchNorm()
                setattr(self, "conv%d" % j, conv)   # registers the child
                setattr(self, "bn%d" % j, bn)
                self.convs.append(conv)
                self.bns.append(bn)
            if self._project:
                self.proj = _conv(channels, 1, stride, 0)
                if not pre_act:
                    self.proj_bn = nn.BatchNorm()

    def hybrid_forward(self, F, x):
        if self._pre_act:
            # v2: shared BN-ReLU, shortcut off the activated tensor
            y = F.Activation(self.bns[0](x), act_type="relu")
            shortcut = self.proj(y) if self._project else x
            h = self.convs[0](y)
            for conv, bn in zip(self.convs[1:], self.bns[1:]):
                h = conv(F.Activation(bn(h), act_type="relu"))
            return h + shortcut
        # v1: conv-BN(-ReLU) chain, ReLU after the addition
        h = x
        last = len(self.convs) - 1
        for j, (conv, bn) in enumerate(zip(self.convs, self.bns)):
            h = bn(conv(h))
            if j != last:
                h = F.Activation(h, act_type="relu")
        shortcut = self.proj_bn(self.proj(x)) if self._project else x
        return F.Activation(h + shortcut, act_type="relu")


class SpaceToDepthStem(HybridBlock):
    """TPU-friendly ImageNet stem: space-to-depth(2) the input, then a
    4x4/stride-1 conv on 12 channels instead of 7x7/stride-2 on 3.

    The MXU is a 128x128 systolic array; a 3-input-channel kernel fills
    3/128 of its lanes, so the classic stem runs at ~2% MXU utilization
    regardless of how XLA tiles it.  The s2d form is the standard TPU
    fix (used by MLPerf ResNet submissions): same output grid, a
    receptive-field superset of the 7x7 (its taps map to
    w4[o, a*2C+b*C+c, dp, dq] = w7[o, c, 2dp+a-1, 2dq+b-1] with the
    out-of-range row/col -1 taps zero — see tests/test_gluon.py
    equivalence test), and 4x the input-lane occupancy at half the
    spatial extent.
    Opt-in via get_model(..., stem='s2d'); weight shape differs from
    the reference checkpoint format, which is why it is not default.
    """

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv = _conv(channels, 4, 1, 2)

    def hybrid_forward(self, F, x):
        h = self.conv(F.space_to_depth(x, block_size=2))
        # k=4/pad=2 yields one extra row/col vs the 7x7/s2 grid; the
        # first 7x7 tap row 2i-3 sits at tap (dp=1, a=0) here, so the
        # aligned output is the leading slice
        return F.slice(h, begin=(0, 0, 0, 0), end=(None, None, -1, -1))


class _ResNet(HybridBlock):
    def __init__(self, depth, pre_act, classes=1000, thumbnail=False,
                 stem="conv7", **kwargs):
        super().__init__(**kwargs)
        bottleneck, units, widths = _SPECS[depth]
        self._pre_act = pre_act
        with self.name_scope():
            body = nn.HybridSequential(prefix="")
            if pre_act:
                body.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:      # CIFAR-style 32x32 stem
                body.add(_conv(_STEM_CHANNELS, 3, 1, 1))
            else:              # ImageNet stem
                if stem == "s2d":
                    body.add(SpaceToDepthStem(_STEM_CHANNELS))
                elif stem == "conv7":
                    body.add(_conv(_STEM_CHANNELS, 7, 2, 3))
                else:
                    raise ValueError("stem must be 'conv7' or 's2d'")
                body.add(nn.BatchNorm())
                body.add(nn.Activation("relu"))
                body.add(nn.MaxPool2D(3, 2, 1))
            in_ch = _STEM_CHANNELS
            for s, (n_units, width) in enumerate(zip(units, widths)):
                stage = nn.HybridSequential(prefix="stage%d_" % (s + 1))
                with stage.name_scope():
                    for u in range(n_units):
                        stage.add(ResidualUnit(
                            width, 2 if (s > 0 and u == 0) else 1,
                            in_ch, bottleneck, pre_act, prefix=""))
                        in_ch = width
                body.add(stage)
            if pre_act:
                body.add(nn.BatchNorm())
                body.add(nn.Activation("relu"))
            body.add(nn.GlobalAvgPool2D())
            body.add(nn.Flatten())
            self.features = body
            self.output = nn.Dense(classes, in_units=in_ch)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class ResNetV1(_ResNet):
    def __init__(self, depth=50, **kwargs):
        super().__init__(depth, pre_act=False, **kwargs)


class ResNetV2(_ResNet):
    def __init__(self, depth=50, **kwargs):
        super().__init__(depth, pre_act=True, **kwargs)


# the reference's block classes, kept as aliases for API compatibility
def BasicBlockV1(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    return ResidualUnit(channels, stride, in_channels, False, False,
                        **kwargs)


def BasicBlockV2(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    return ResidualUnit(channels, stride, in_channels, False, True,
                        **kwargs)


def BottleneckV1(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    return ResidualUnit(channels, stride, in_channels, True, False,
                        **kwargs)


def BottleneckV2(channels, stride, downsample=False, in_channels=0,
                 **kwargs):
    return ResidualUnit(channels, stride, in_channels, True, True,
                        **kwargs)


def get_resnet(version, num_layers, pretrained=False, ctx=None,
               **kwargs):
    if num_layers not in _SPECS:
        raise ValueError("no resnet-%s; depths: %s"
                         % (num_layers, sorted(_SPECS)))
    if version not in (1, 2):
        raise ValueError("resnet version must be 1 or 2")
    if pretrained:
        raise ValueError("pretrained weights are unavailable in this "
                         "zero-egress build; load_parameters() manually")
    cls = ResNetV1 if version == 1 else ResNetV2
    return cls(num_layers, **kwargs)


def _factory(version, depth):
    def make(**kwargs):
        return get_resnet(version, depth, **kwargs)
    make.__name__ = "resnet%d_v%d" % (depth, version)
    make.__doc__ = "ResNet-%d v%d" % (depth, version)
    return make


resnet18_v1 = _factory(1, 18)
resnet34_v1 = _factory(1, 34)
resnet50_v1 = _factory(1, 50)
resnet101_v1 = _factory(1, 101)
resnet152_v1 = _factory(1, 152)
resnet18_v2 = _factory(2, 18)
resnet34_v2 = _factory(2, 34)
resnet50_v2 = _factory(2, 50)
resnet101_v2 = _factory(2, 101)
resnet152_v2 = _factory(2, 152)
