"""SqueezeNet 1.0/1.1 (capability parity with the reference zoo;
Iandola et al. 2016).  Written plan-table-first: each version is a flat
layer plan — "fire" entries expand to one Fire module (squeeze 1x1 +
parallel 1x1/3x3 expands, concatenated on channels).
"""

from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]

# fire s: squeeze width s, expands (4*s, 4*s) — the paper's e=4s ratio
_PLANS = {
    "1.0": [("conv", 96, 7), "pool",
            ("fire", 16), ("fire", 16), ("fire", 32), "pool",
            ("fire", 32), ("fire", 48), ("fire", 48), ("fire", 64),
            "pool", ("fire", 64)],
    "1.1": [("conv", 64, 3), "pool",
            ("fire", 16), ("fire", 16), "pool",
            ("fire", 32), ("fire", 32), "pool",
            ("fire", 48), ("fire", 48), ("fire", 64), ("fire", 64)],
}


class Fire(HybridBlock):
    """squeeze(1x1) -> [expand1x1 | expand3x3] -> concat."""

    def __init__(self, squeeze, **kwargs):
        super().__init__(**kwargs)
        expand = 4 * squeeze
        with self.name_scope():
            self.squeeze = nn.Conv2D(squeeze, kernel_size=1)
            self.left = nn.Conv2D(expand, kernel_size=1)
            self.right = nn.Conv2D(expand, kernel_size=3, padding=1)

    def hybrid_forward(self, F, x):
        s = F.relu(self.squeeze(x))
        return F.concat(F.relu(self.left(s)), F.relu(self.right(s)),
                        dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in _PLANS:
            raise ValueError("version must be one of %s"
                             % sorted(_PLANS))
        with self.name_scope():
            f = nn.HybridSequential(prefix="")
            for step in _PLANS[version]:
                if step == "pool":
                    f.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                elif step[0] == "conv":
                    f.add(nn.Conv2D(step[1], kernel_size=step[2],
                                    strides=2))
                    f.add(nn.Activation("relu"))
                else:
                    f.add(Fire(step[1]))
            f.add(nn.Dropout(0.5))
            self.features = f
            head = nn.HybridSequential(prefix="")
            head.add(nn.Conv2D(classes, kernel_size=1))
            head.add(nn.Activation("relu"))
            head.add(nn.GlobalAvgPool2D())
            head.add(nn.Flatten())
            self.output = head

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(**kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return SqueezeNet("1.1", **kwargs)
