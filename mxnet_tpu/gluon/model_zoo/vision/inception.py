"""Inception-v3 (reference capability: gluon/model_zoo/vision/inception.py;
architecture from Szegedy et al. 2015, "Rethinking the Inception
Architecture").  Written config-table-first: each inception stage is a
list of branch specs, and one `_Branches` block concatenates them — the
whole network still compiles to a single XLA program under hybridize.
"""

from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _conv_bn(channels, kernel, stride=1, pad=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                      padding=pad, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _chain(specs):
    """specs: list of (channels, kernel, stride, pad) conv specs, or the
    strings 'avgpool'/'maxpool' for the in-branch pooling steps."""
    seq = nn.HybridSequential(prefix="")
    for s in specs:
        if s == "avgpool":
            seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
        elif s == "maxpool":
            seq.add(nn.MaxPool2D(pool_size=3, strides=2))
        else:
            seq.add(_conv_bn(*s))
    return seq


class _Branches(HybridBlock):
    """Run each branch on the same input and concat on channels."""

    def __init__(self, branch_specs, **kwargs):
        super().__init__(**kwargs)
        self.branches = []
        for i, specs in enumerate(branch_specs):
            b = _chain(specs)
            self.register_child(b)
            setattr(self, "branch%d" % i, b)
            self.branches.append(b)

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self.branches], dim=1)


# (channels, kernel, stride, pad); kernels may be rectangular tuples.
def _stage_a(pool_ch):
    return [[(64, 1, 1, 0)],
            [(48, 1, 1, 0), (64, 5, 1, 2)],
            [(64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 1, 1)],
            ["avgpool", (pool_ch, 1, 1, 0)]]


def _stage_b():
    return [[(384, 3, 2, 0)],
            [(64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 2, 0)],
            ["maxpool"]]


def _stage_c(ch7):
    return [[(192, 1, 1, 0)],
            [(ch7, 1, 1, 0), (ch7, (1, 7), 1, (0, 3)),
             (192, (7, 1), 1, (3, 0))],
            [(ch7, 1, 1, 0), (ch7, (7, 1), 1, (3, 0)),
             (ch7, (1, 7), 1, (0, 3)), (ch7, (7, 1), 1, (3, 0)),
             (192, (1, 7), 1, (0, 3))],
            ["avgpool", (192, 1, 1, 0)]]


def _stage_d():
    return [[(192, 1, 1, 0), (320, 3, 2, 0)],
            [(192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
             (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)],
            ["maxpool"]]


class _StageE(HybridBlock):
    """The expanded 8x8 stage: two of its branches themselves fork."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.b0 = _chain([(320, 1, 1, 0)])
        self.b1_stem = _chain([(384, 1, 1, 0)])
        self.b1a = _chain([(384, (1, 3), 1, (0, 1))])
        self.b1b = _chain([(384, (3, 1), 1, (1, 0))])
        self.b2_stem = _chain([(448, 1, 1, 0), (384, 3, 1, 1)])
        self.b2a = _chain([(384, (1, 3), 1, (0, 1))])
        self.b2b = _chain([(384, (3, 1), 1, (1, 0))])
        self.b3 = _chain(["avgpool", (192, 1, 1, 0)])
        for blk in (self.b0, self.b1_stem, self.b1a, self.b1b,
                    self.b2_stem, self.b2a, self.b2b, self.b3):
            self.register_child(blk)

    def hybrid_forward(self, F, x):
        y1 = self.b1_stem(x)
        y2 = self.b2_stem(x)
        return F.concat(self.b0(x), self.b1a(y1), self.b1b(y1),
                        self.b2a(y2), self.b2b(y2), self.b3(x), dim=1)


class Inception3(HybridBlock):
    """Inception v3; input 3x299x299."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            f = nn.HybridSequential(prefix="")
            f.add(_conv_bn(32, 3, 2, 0))
            f.add(_conv_bn(32, 3, 1, 0))
            f.add(_conv_bn(64, 3, 1, 1))
            f.add(nn.MaxPool2D(pool_size=3, strides=2))
            f.add(_conv_bn(80, 1, 1, 0))
            f.add(_conv_bn(192, 3, 1, 0))
            f.add(nn.MaxPool2D(pool_size=3, strides=2))
            for pool_ch in (32, 64, 64):
                f.add(_Branches(_stage_a(pool_ch)))
            f.add(_Branches(_stage_b()))
            for ch7 in (128, 160, 160, 192):
                f.add(_Branches(_stage_c(ch7)))
            f.add(_Branches(_stage_d()))
            f.add(_StageE())
            f.add(_StageE())
            f.add(nn.GlobalAvgPool2D())
            f.add(nn.Dropout(0.5))
            self.features = f
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=None, classes=1000, **kwargs):
    if pretrained:
        raise ValueError("no hosted pretrained weights in this build; "
                         "use load_parameters() with a local file")
    return Inception3(classes=classes, **kwargs)
