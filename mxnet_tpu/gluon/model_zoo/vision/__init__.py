"""Vision model zoo with a name registry.

Reference capability: python/mxnet/gluon/model_zoo/vision/__init__.py:91
(`get_model`) plus model_store.py pretrained downloads.  This build has no
hosted weight store (zero egress); ``pretrained=True`` therefore raises
with a pointer to ``load_parameters`` for locally saved weights.
"""

from . import alexnet as _m_alexnet
from . import densenet as _m_densenet
from . import inception as _m_inception
from . import mobilenet as _m_mobilenet
from . import resnet as _m_resnet
from . import squeezenet as _m_squeezenet
from . import vgg as _m_vgg

_MODULES = (_m_alexnet, _m_densenet, _m_inception, _m_mobilenet, _m_resnet,
            _m_squeezenet, _m_vgg)

_factories = {}
for _mod in _MODULES:
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        globals()[_name] = _obj
        if callable(_obj) and _name[0].islower():
            _factories[_name] = _obj

# reference naming aliases (resnet18_v1 <-> resnet18-ish lookups keep the
# canonical underscore form; get_model lowercases and strips dashes)


def get_model(name, pretrained=False, root=None, **kwargs):
    """Return a model by name (reference: vision/__init__.py:91).

    ``pretrained=True`` loads weights from the local model directory
    (see model_store.py — no download path in this offline build)."""
    name = name.lower().replace("-", "_")
    if name not in _factories:
        raise ValueError(
            "Model %r not found. Available: %s"
            % (name, ", ".join(sorted(_factories))))
    net = _factories[name](**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_parameters(get_model_file(name, root=root))
    return net


__all__ = [n for m in _MODULES for n in m.__all__] + ["get_model"]
