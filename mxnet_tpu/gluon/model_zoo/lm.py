"""LSTM language model — the reference's own LM headline shape
(example/rnn PTB models: Embedding + fused-RNN LSTM stack + head; the
fused op is `lax.scan` here, ops/rnn.py).  Shared by
tools/benchmark_lm.py --arch lstm and the trainer tests."""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn, rnn

__all__ = ["LSTMLM", "get_lstm_lm"]


class LSTMLM(HybridBlock):
    def __init__(self, vocab, dim, layers, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = nn.Embedding(vocab, dim)
            self.lstm = rnn.LSTM(dim, num_layers=layers, layout="NTC")
            self.head = nn.Dense(vocab, use_bias=False, flatten=False)

    def hybrid_forward(self, F, x):
        return self.head(self.lstm(self.embed(x)))


def get_lstm_lm(vocab=10000, dim=650, layers=2, **kwargs):
    """Defaults: the reference PTB 'medium' config (2x650)."""
    return LSTMLM(vocab, dim, layers, **kwargs)
