"""Pretrained-weight store (reference:
python/mxnet/gluon/model_zoo/model_store.py — sha1-indexed download of
pretrained .params from the MXNet S3 bucket).

Offline stance: this build has no network egress, so there is no
download path.  ``get_model_file`` resolves weights from the local model
directory only (``$MXNET_HOME/models`` or ``~/.mxnet/models`` — the same
location the reference caches into), so checkpoints placed there by the
user (or exported by ``Block.save_parameters``) load exactly like the
reference's pretrained flow; a missing file raises with instructions
instead of attempting a download."""

from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]


def _model_dir():
    return os.path.join(
        os.environ.get("MXNET_HOME",
                       os.path.join(os.path.expanduser("~"), ".mxnet")),
        "models")


def get_model_file(name, root=None):
    """Path to ``<root>/<name>.params``; raises FileNotFoundError with
    the offline explanation when absent (reference: model_store.py
    get_model_file — which would download on miss)."""
    root = root or _model_dir()
    path = os.path.join(root, "%s.params" % name)
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        "pretrained weights %r not found at %s. This build has no "
        "network egress: place the .params file there yourself (any "
        "checkpoint saved with save_parameters works), then retry."
        % (name, path))


def purge(root=None):
    """Remove cached model files (reference: model_store.py purge)."""
    root = root or _model_dir()
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.unlink(os.path.join(root, f))
