"""Pretrained-weight store (reference:
python/mxnet/gluon/model_zoo/model_store.py — sha1-indexed download of
pretrained .params from the MXNet S3 bucket).

Offline stance: this build has no network egress, so there is no
download path.  ``get_model_file`` resolves weights from the local model
directory only (``$MXNET_HOME/models`` or ``~/.mxnet/models`` — the same
location the reference caches into), so checkpoints placed there by the
user (or exported by ``Block.save_parameters``) load exactly like the
reference's pretrained flow; a missing file raises with instructions
instead of attempting a download.

The read probe runs under the resilience retry policy: transient
storage errors (an NFS/FUSE model dir flaking, the reference's download
path retried the same way) back off and retry, while a genuinely
missing file fails fast.
"""

from __future__ import annotations

import os
import time

__all__ = ["get_model_file", "purge"]

# retry policy for the store probe; _sleep is module-level so tests can
# stub the clock out.  Non-transient shapes (missing file, permission
# denied, path-is-a-directory) fail fast — only plausible storage
# flakes burn backoff
_sleep = time.sleep
_RETRY = dict(attempts=4, base_delay=0.05, max_delay=0.5,
              retry_on=(OSError,),
              give_up_on=(FileNotFoundError, PermissionError,
                          IsADirectoryError, NotADirectoryError))


def _model_dir():
    return os.path.join(
        os.environ.get("MXNET_HOME",
                       os.path.join(os.path.expanduser("~"), ".mxnet")),
        "models")


def _probe(path):
    """Open-and-touch the weight file; OSError here is how flaky
    network storage announces itself."""
    with open(path, "rb") as f:
        f.read(1)


def get_model_file(name, root=None):
    """Path to ``<root>/<name>.params``; raises FileNotFoundError with
    the offline explanation when absent (reference: model_store.py
    get_model_file — which would download on miss).  Transient read
    failures are retried with jittered backoff."""
    from ...resilience.retry import retry_call
    root = root or _model_dir()
    path = os.path.join(root, "%s.params" % name)
    try:
        retry_call(_probe, (path,), sleep=_sleep, **_RETRY)
    except FileNotFoundError:
        raise FileNotFoundError(
            "pretrained weights %r not found at %s. This build has no "
            "network egress: place the .params file there yourself (any "
            "checkpoint saved with save_parameters works), then retry."
            % (name, path))
    return path


def purge(root=None):
    """Remove cached model files (reference: model_store.py purge)."""
    root = root or _model_dir()
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.unlink(os.path.join(root, f))
