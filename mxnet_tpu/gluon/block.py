"""Gluon Block / HybridBlock / SymbolBlock.

Reference: ``python/mxnet/gluon/block.py`` (1,162 LoC: Block:127,
HybridBlock:673, hybridize -> CachedOp block.py:787,797, SymbolBlock:954)
over ``src/imperative/cached_op.cc``.

TPU-native CachedOp: hybridizing traces ``hybrid_forward`` once with Symbol
proxies, then compiles the whole block into a single jitted XLA program
(static_alloc/static_shape are implied — XLA plans memory at compile time;
the reference's StaticAllocMemory/StaticRunOps machinery, cached_op.cc:469+,
is the compiler's job here).  Under autograd the entire cached program is
one tape node, so backward is one fused VJP program.
"""

from __future__ import annotations

import copy
import re
import threading
from collections import OrderedDict

import jax

from ..base import MXNetError, dtype_name
from ..context import Context, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import symbol as sym_mod
from .. import autograd
from .parameter import (Parameter, ParameterDict,
                        DeferredInitializationError)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scoping for Blocks (reference: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_unique(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_GLOBAL_NAME_COUNTER = {}


def _name_unique(hint):
    n = _GLOBAL_NAME_COUNTER.get(hint, 0)
    _GLOBAL_NAME_COUNTER[hint] = n + 1
    return "%s%d" % (hint, n)


def _flatten(args, inout_str):
    if isinstance(args, NDArray) or isinstance(args, sym_mod.Symbol):
        return [args], int(0)
    if args is None:
        return [None], int(-1)
    assert isinstance(args, (list, tuple)), \
        "HybridBlock %s must be (nested) list of Symbol or NDArray, " \
        "but got %s of type %s" % (inout_str, str(args), str(type(args)))
    flat = []
    fmts = []
    for i in args:
        arg, fmt = _flatten(i, inout_str)
        flat.extend(arg)
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == -1:
            return None, args[1:]
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block:
    """Base class for all layers and models (reference: block.py Block:127).
    """

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr) \
            if modstr else "%s()" % self.__class__.__name__

    def __setattr__(self, name, value):
        existing = getattr(self, name, None)
        if isinstance(existing, (Parameter, Block)) and \
                not isinstance(value, type(existing)):
            raise TypeError("Changing attribute type for %s from %s to %s"
                            " is not allowed." % (
                                name, type(existing), type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _check_container_with_block(self):
        pass

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of this Block and its children
        (reference: block.py collect_params)."""
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks[len(self._forward_hooks)] = hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks[len(self._forward_pre_hooks)] = hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer
        if init is None:
            init = initializer.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    # -- checkpointing -----------------------------------------------------
    def save_parameters(self, filename):
        """Save parameters (reference: block.py save_parameters:315).
        Keys are stripped of the block prefix so files are
        architecture-portable."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val.data() for key, val in params.items()}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False):
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        for name in params if not allow_missing else []:
            if name not in loaded:
                raise AssertionError(
                    "Parameter %r is missing in file %r" % (name, filename))
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise AssertionError(
                        "Parameter %r loaded from file %r is not present "
                        "in this block" % (name, filename))
                continue
            param = params[name]
            if param._data is None and param._deferred_init is not None:
                param.shape = loaded[name].shape
                param._finish_deferred_init()
            elif param._data is None:
                param._shape = loaded[name].shape
                param.initialize(ctx=ctx or current_context())
            param.set_data(loaded[name])

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # legacy-name API
    save_params = save_parameters

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    # -- execution ---------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        # minimal: run a forward and report parameter count
        out = self(*inputs)
        n = 0
        for p in self.collect_params().values():
            if p._data is not None:
                n += p.data().size
        print("Total params: %d" % n)
        return out


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line
                                    for line in lines)


class _CachedGraph:
    """Compiled trace of a HybridBlock (the CachedOp equivalent)."""

    def __init__(self, block, flat_inputs):
        # trace with symbol proxies
        data_syms = [sym_mod.var("data%d" % i)
                     for i in range(len(flat_inputs))]
        param_syms = {n: p.var() for n, p in block._reg_params.items()}
        with block._trace_scope():
            if len(data_syms) == 1:
                out = block.hybrid_forward(sym_mod, data_syms[0],
                                           **param_syms)
            else:
                out = block.hybrid_forward(sym_mod, *data_syms, **param_syms)
        flat_out, self._out_fmt = _flatten(out, "output")
        self.symbol = sym_mod.Group(flat_out) if len(flat_out) > 1 \
            else flat_out[0]
        self.input_names = ["data%d" % i for i in range(len(flat_inputs))]
        args = self.symbol.list_arguments()
        auxs = set(self.symbol.list_auxiliary_states())
        self.param_names = [a for a in args if a not in self.input_names]
        self.aux_names = list(self.symbol.list_auxiliary_states())
        from ..executor import _build_eval
        self._eval_train = _build_eval(self.symbol, True)
        self._eval_infer = _build_eval(self.symbol, False)
        self._jit_train = jax.jit(self._eval_train)
        self._jit_infer = jax.jit(self._eval_infer)
        self._vjp_jit = {}  # per training-mode compiled vjp
        del auxs

    def run(self, block, flat_inputs):
        params = {p.name: p for p in block.collect_params().values()}
        arg_map = {n: x._data for n, x in zip(self.input_names, flat_inputs)}
        diff_names = []
        for n in self.param_names:
            arr = params[n].data()
            arg_map[n] = arr._data
            diff_names.append(n)
        aux_map = {n: params[n].data()._data for n in self.aux_names}
        training = autograd.is_training()
        key = _next_block_key()
        fn = self._jit_train if training else self._jit_infer
        outs, auxu = fn(arg_map, aux_map, key)
        for n, v in auxu.items():
            params[n].data()._data = v
        out_nds = [NDArray(o) for o in outs]
        if autograd.is_recording():
            # one tape node for the whole cached graph, with a per-graph
            # COMPILED vjp (one XLA program, reused every step — the
            # CachedOp::Backward static path, cached_op.cc:961)
            input_nds = list(flat_inputs) + [params[n].data()
                                             for n in diff_names]
            in_names = tuple(self.input_names) + tuple(diff_names)
            if training not in self._vjp_jit:
                # differentiate the SAME mode's graph that ran forward
                eval_fn = self._eval_train if training else self._eval_infer

                def vjp_run(arrays, aux, k, cots):
                    def f(arrs):
                        amap = dict(zip(in_names, arrs))
                        o, _ = eval_fn(amap, aux, k)
                        return tuple(o)
                    _, vjp = jax.vjp(f, tuple(arrays))
                    return vjp(tuple(cots))[0]

                self._vjp_jit[training] = jax.jit(vjp_run)
            arrays = tuple(x._data for x in input_nds)
            aux_snapshot = dict(aux_map)
            vjp_jit = self._vjp_jit[training]
            raw_outs = list(outs)

            def custom_vjp(out_cots):
                cots = tuple(
                    c.astype(o.dtype) if c.dtype != o.dtype else c
                    for c, o in zip(out_cots, raw_outs))
                return list(vjp_jit(arrays, aux_snapshot, key, cots))

            autograd.record_op(("__custom__", custom_vjp), input_nds,
                               out_nds)
        out, _ = _regroup(out_nds, self._out_fmt)
        return out


# lazily initialized: creating a PRNG key eagerly would force jax backend
# initialization at `import mxnet_tpu`
_block_key_state = [None, 0]


def _next_block_key():
    if _block_key_state[0] is None:
        _block_key_state[0] = jax.random.PRNGKey(17)
    _block_key_state[1] += 1
    return jax.random.fold_in(_block_key_state[0], _block_key_state[1])


class HybridBlock(Block):
    """Block that can be traced and compiled (reference: HybridBlock:673)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = None
        self._flags = {}

    def _trace_scope(self):
        import contextlib

        @contextlib.contextmanager
        def scope():
            yield
        return scope()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._cached_graph = None
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_graph = None
        super().cast(dtype)

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            if not isinstance(block, SymbolBlock):
                pass
        super().register_child(block, name)
        self._cached_graph = None

    def infer_shape(self, *args):
        """Infer deferred parameter shapes from input shapes via the
        symbolic trace (reference: block.py _deferred_infer_shape)."""
        self._infer_attrs(*args)

    def _infer_attrs(self, *args):
        flat, _ = _flatten(args, "input")
        data_shapes = {"data%d" % i: x.shape for i, x in enumerate(flat)}
        data_syms = [sym_mod.var("data%d" % i) for i in range(len(flat))]
        param_syms = {n: sym_mod.var(p.name)
                      for n, p in self._reg_params.items()}
        out = self.hybrid_forward(sym_mod, *data_syms, **param_syms)
        flat_out, _ = _flatten(out, "output")
        symbol = sym_mod.Group(flat_out) if len(flat_out) > 1 \
            else flat_out[0]
        from ..symbol.symbol import _infer_shapes
        _, var_sh = _infer_shapes(symbol, data_shapes, partial=True)
        params = {p.name: p for p in self.collect_params().values()}
        for name, shape in var_sh.items():
            if name in params and shape is not None:
                params[name].shape = tuple(shape)
        for p in params.values():
            if p._deferred_init is not None and p.shape is not None and \
                    all(s > 0 for s in p.shape):
                p._finish_deferred_init()

    def forward(self, x, *args):
        """Dispatch: Symbol input -> symbolic trace (used when a parent is
        being hybridized); hybridized -> cached XLA program; else imperative
        hybrid_forward with F=nd."""
        if isinstance(x, sym_mod.Symbol):
            param_syms = {n: p.var() for n, p in self._reg_params.items()}
            return self.hybrid_forward(sym_mod, x, *args, **param_syms)
        if self._active:
            if self._cached_graph is None:
                flat, self._in_fmt = _flatten([x] + list(args), "input")
                try:
                    self._ensure_params(x, *args)
                    self._cached_graph = _CachedGraph(self, flat)
                except DeferredInitializationError:
                    raise
            flat, _ = _flatten([x] + list(args), "input")
            return self._cached_graph.run(self, flat)
        # imperative path
        self._ensure_params(x, *args)
        params = {n: p.data() for n, p in self._reg_params.items()}
        return self.hybrid_forward(nd, x, *args, **params)

    def _ensure_params(self, *args):
        deferred = [p for p in self.collect_params().values()
                    if p._deferred_init is not None]
        if deferred:
            self._infer_attrs(*args)
            still = [p for p in self.collect_params().values()
                     if p._deferred_init is not None]
            if still and args and all(isinstance(a, NDArray)
                                      for a in args):
                # graph shape inference couldn't resolve everything
                # (e.g. an RNN layer's packed weights); one imperative
                # pass lets each child resolve its own shapes eagerly
                try:
                    params = {n: p.data()
                              for n, p in self._reg_params.items()}
                    self.hybrid_forward(nd, *args, **params)
                except DeferredInitializationError:
                    pass
        # trigger friendly error if not initialized at all
        for p in self.collect_params().values():
            p._check_initialized()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Export to symbol JSON + params (reference: block.py export —
        format: path-symbol.json + path-NNNN.params)."""
        if self._cached_graph is None:
            raise RuntimeError(
                "Please call hybridize and run forward at least once before "
                "calling export.")
        sym_file = "%s-symbol.json" % path
        self._cached_graph.symbol.save(sym_file)
        arg_dict = {}
        params = {p.name: p for p in self.collect_params().values()}
        for name in self._cached_graph.param_names:
            arg_dict["arg:%s" % name] = params[name].data()
        for name in self._cached_graph.aux_names:
            arg_dict["aux:%s" % name] = params[name].data()
        nd.save("%s-%04d.params" % (path, epoch), arg_dict)
        return sym_file


class SymbolBlock(HybridBlock):
    """Wrap a Symbol as a Block (reference: block.py SymbolBlock:954)."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        symbol = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(symbol, inputs)
        if param_file is not None:
            params = nd.load(param_file)
            arg_params = {}
            for k, v in params.items():
                if k.startswith(("arg:", "aux:")):
                    arg_params[k[4:]] = v
                else:
                    arg_params[k] = v
            for name, param in ret.collect_params().items():
                if name in arg_params:
                    param._shape = arg_params[name].shape
                    param.initialize(ctx=ctx or current_context())
                    param.set_data(arg_params[name])
        return ret

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(outputs)
        if isinstance(inputs, sym_mod.Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names:
            if name not in self._input_names:
                self.params.get(name, allow_deferred_init=True,
                                grad_req="write")
        for name in outputs.list_auxiliary_states():
            self.params.get(name, allow_deferred_init=True, grad_req="null")
        self._jit_cache = {}

    def forward(self, *args):
        flat, _ = _flatten(list(args), "input")
        arg_map = {n: x._data for n, x in zip(self._input_names, flat)}
        params = dict(self.collect_params().items())
        aux_names = set(self._symbol.list_auxiliary_states())
        aux_map = {}
        diff_names = []
        for name in self._symbol.list_arguments():
            if name in arg_map:
                continue
            arg_map[name] = params[name].data()._data
            diff_names.append(name)
        for name in aux_names:
            aux_map[name] = params[name].data()._data
        training = autograd.is_training()
        key = ("train" if training else "infer")
        if key not in self._jit_cache:
            from ..executor import _build_eval
            ev = _build_eval(self._symbol, training)
            self._jit_cache[key] = (ev, jax.jit(ev))
        ev, jfn = self._jit_cache[key]
        key2 = _next_block_key()
        outs, auxu = jfn(arg_map, aux_map, key2)
        for n, v in auxu.items():
            params[n].data()._data = v
        out_nds = [NDArray(o) for o in outs]
        if autograd.is_recording():
            in_names = tuple(self._input_names) + tuple(diff_names)
            input_nds = list(flat) + [params[n].data() for n in diff_names]
            aux_snapshot = dict(aux_map)
            vkey = "vjp_" + ("train" if training else "infer")
            if vkey not in self._jit_cache:
                def vjp_run(arrays, aux, k, cots):
                    def f(arrs):
                        amap = dict(zip(in_names, arrs))
                        o, _ = ev(amap, aux, k)
                        return tuple(o)
                    _, vjp = jax.vjp(f, tuple(arrays))
                    return vjp(tuple(cots))[0]
                self._jit_cache[vkey] = jax.jit(vjp_run)
            vjp_jit = self._jit_cache[vkey]
            arrays = tuple(x._data for x in input_nds)
            raw_outs = list(outs)

            def custom_vjp(out_cots):
                cots = tuple(
                    c.astype(o.dtype) if c.dtype != o.dtype else c
                    for c, o in zip(out_cots, raw_outs))
                return list(vjp_jit(arrays, aux_snapshot, key2, cots))

            autograd.record_op(("__custom__", custom_vjp), input_nds,
                               out_nds)
        if len(out_nds) == 1:
            return out_nds[0]
        return out_nds

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
