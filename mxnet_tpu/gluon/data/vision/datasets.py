"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py:
36-264 — MNIST, FashionMNIST, CIFAR10/100, ImageRecordDataset,
ImageFolderDataset).

This environment has no network egress, so datasets read from a local
``root`` (files in the reference's on-disk formats) and raise a clear error
when files are absent instead of downloading.
"""

from __future__ import annotations

import os
import pickle

import numpy as _np

from .... import ndarray as nd
from ....io.io import _read_idx_images, _read_idx_labels
from .. import dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _IdxDataset(dataset.Dataset):
    """Shared base for idx-format image/label pairs."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root, train=True, transform=None):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._get_data()

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise IOError(
            "%s not found under %s. This build has no network egress: "
            "place the idx files there manually." % (base, self._root))

    def _get_data(self):
        img_base, lbl_base = self._train_files if self._train \
            else self._test_files
        data = _read_idx_images(self._find(img_base))
        label = _read_idx_labels(self._find(lbl_base))
        self._data = data.reshape(data.shape[0], data.shape[1],
                                  data.shape[2], 1)
        self._label = label.astype(_np.int32)

    def __getitem__(self, idx):
        img = nd.array(self._data[idx], dtype="uint8")
        lbl = int(self._label[idx])
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl

    def __len__(self):
        return len(self._label)


class MNIST(_IdxDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class FashionMNIST(_IdxDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(dataset.Dataset):
    """CIFAR-10 from the python pickle batches
    (reference: datasets.py CIFAR10 reads the binary .bin variant)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"), train=True,
                 transform=None):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._get_data()

    def _batches(self):
        if self._train:
            return ["data_batch_%d" % i for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        data = []
        labels = []
        base = self._root
        sub = os.path.join(base, "cifar-10-batches-py")
        if os.path.isdir(sub):
            base = sub
        for name in self._batches():
            path = os.path.join(base, name)
            if not os.path.exists(path):
                raise IOError(
                    "%s not found (no network egress; place CIFAR-10 "
                    "python batches under %s)" % (path, self._root))
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            data.append(batch[b"data"])
            labels.extend(batch[b"labels"])
        data = _np.concatenate(data).reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)  # NHWC uint8 like reference
        self._label = _np.asarray(labels, _np.int32)

    def __getitem__(self, idx):
        img = nd.array(self._data[idx], dtype="uint8")
        lbl = int(self._label[idx])
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl

    def __len__(self):
        return len(self._label)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _batches(self):
        return ["train"] if self._train else ["test"]

    def _get_data(self):
        base = self._root
        sub = os.path.join(base, "cifar-100-python")
        if os.path.isdir(sub):
            base = sub
        name = self._batches()[0]
        path = os.path.join(base, name)
        if not os.path.exists(path):
            raise IOError("%s not found (no network egress)" % path)
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        data = batch[b"data"].reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._label = _np.asarray(batch[key], _np.int32)


class ImageRecordDataset(dataset.RecordFileDataset):
    """Images from a RecordIO file (reference: datasets.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        record = super().__getitem__(idx)
        header, img = recordio.unpack_img(record)
        img = nd.array(img, dtype="uint8")
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(dataset.Dataset):
    """class-per-subfolder image dataset (reference: datasets.py
    ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1].lower()
                if ext not in self._exts:
                    continue
                self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = _np.load(path)
        else:
            from PIL import Image
            img = _np.asarray(Image.open(path))
        img = nd.array(img, dtype="uint8")
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
