"""Vision transforms (reference:
python/mxnet/gluon/data/vision/transforms.py — ToTensor, Normalize,
Resize, crops, flips, color jitter)."""

from __future__ import annotations

import numpy as _np

from .... import ndarray as nd
from ....ndarray import NDArray
from ...nn.basic_layers import Sequential, HybridSequential
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomLighting"]


class Compose(Sequential):
    """Chain transforms (reference: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1]
    (reference: to_tensor op, src/operator/image/image_random.cc)."""

    def hybrid_forward(self, F, x):
        x = F.Cast(x, dtype="float32")
        x = x / 255.0
        if hasattr(x, "ndim") and x.ndim == 4:
            return F.transpose(x, axes=(0, 3, 1, 2))
        return F.transpose(x, axes=(2, 0, 1))


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, _np.float32).reshape(-1, 1, 1)
        self._std = _np.asarray(std, _np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        return (x - nd.array(self._mean)) / nd.array(self._std)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        arr = x._data.astype(jnp.float32)
        h, w = self._size[1], self._size[0]
        out = jax.image.resize(arr, (h, w, arr.shape[-1]), "bilinear")
        return NDArray(out.astype(x._data.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return x[y0:y0 + h, x0:x0 + w]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            log_ratio = (_np.log(self._ratio[0]), _np.log(self._ratio[1]))
            aspect = _np.exp(_np.random.uniform(*log_ratio))
            w = int(round(_np.sqrt(target_area * aspect)))
            h = int(round(_np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = _np.random.randint(0, W - w + 1)
                y0 = _np.random.randint(0, H - h + 1)
                crop = x[y0:y0 + h, x0:x0 + w]
                arr = crop._data.astype(jnp.float32)
                out = jax.image.resize(
                    arr, (self._size[1], self._size[0], arr.shape[-1]),
                    "bilinear")
                return NDArray(out.astype(x._data.dtype))
        return CenterCrop(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if _np.random.rand() < 0.5:
            return x.flip(axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._b, self._b)
        return (x.astype("float32") * alpha).clip(0, 255).astype(
            str(x.dtype))


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._c, self._c)
        xf = x.astype("float32")
        gray = xf.mean()
        return ((xf - gray) * alpha + gray).clip(0, 255).astype(
            str(x.dtype))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        alpha = 1.0 + _np.random.uniform(-self._s, self._s)
        xf = x.astype("float32")
        coef = nd.array(_np.array([[[0.299, 0.587, 0.114]]], _np.float32))
        gray = (xf * coef).sum(axis=2, keepdims=True)
        return (xf * alpha + gray * (1 - alpha)).clip(0, 255).astype(
            str(x.dtype))


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference: transforms.py RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], _np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.814],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        alpha = _np.random.normal(0, self._alpha, size=(3,)) \
            .astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return (x.astype("float32") +
                nd.array(rgb.reshape(1, 1, 3))).clip(0, 255).astype(
                    str(x.dtype))
