"""Gluon DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes and ships NDArrays through POSIX shared
memory (cpu_shared context, dataloader.py:26-110).  Here workers are a
thread pool: batch assembly is numpy (releases the GIL in practice) and
device transfer is XLA-async, so threads keep a TPU fed without the
shared-memory machinery; num_workers>0 enables threaded prefetch of whole
batches.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py
    default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return nd.array(data, dtype=str(data.dtype)
                    if data.dtype != _np.float64 else "float32")


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # threaded prefetch: submit up to `prefetch` batch jobs ahead
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            batches = iter(self._batch_sampler)
            futures = []
            try:
                for _ in range(self._prefetch or self._num_workers * 2):
                    futures.append(pool.submit(self._make_batch,
                                               next(batches)))
            except StopIteration:
                pass
            while futures:
                fut = futures.pop(0)
                try:
                    futures.append(pool.submit(self._make_batch,
                                               next(batches)))
                except StopIteration:
                    pass
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
